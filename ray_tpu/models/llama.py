"""Llama-family decoder — the flagship model (BASELINE.md north star:
Llama-2-7B pretraining).

TPU-first design choices:
  * pure-functional params pytree (no module system) so pjit/GSPMD see
    plain arrays with logical-axis annotations (parallel/sharding.py);
  * layers stacked on a leading axis and iterated with `lax.scan` —
    one layer trace instead of n_layers, keeping XLA compile time flat;
  * `jax.checkpoint` around each layer (rematerialization) so HBM
    holds one layer's activations during backward;
  * attention via the Pallas flash kernel (ops/attention.py), ring
    attention (ops/ring_attention.py) when the sequence is sharded
    over `sp`;
  * bfloat16 params/activations, f32 logits for the softmax-xent.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import flash_attention, mha_reference, repeat_kv
from ..ops.moe import moe_ffn_dense, moe_ffn_ep
from ..ops.norms import apply_rotary, rms_norm, rotary_embedding, swiglu
from ..ops.ring_attention import ring_attention
from ..parallel.sharding import Annotated, annotate


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    intermediate: int = 11008
    rope_theta: float = 10000.0
    max_seq_len: int = 4096
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # flash | reference | ring
    remat: bool = True
    # Rematerialization policy: "full" recomputes the whole layer in
    # backward (min HBM, ~33% extra FLOPs); "dots" saves matmul
    # outputs and recomputes only cheap elementwise ops (the standard
    # TPU LLM trade — near-"none" speed at a fraction of the memory);
    # "dots_flash" additionally saves the flash-attention kernel's
    # (out, lse) residuals (ops/attention.py checkpoint names) so the
    # backward never re-runs the forward flash kernel — ~36 MB/layer
    # of HBM at the 410M bench shape buys back ~2.5% of step time;
    # ignored when remat=False.
    remat_policy: str = "full"  # full | dots | dots_flash
    # ---- mixture of experts ----
    #: >0 turns every FFN into a top-k-routed MoE with this many
    #: experts (0 = dense SwiGLU). Experts shard over the `ep` mesh
    #: axis when an ep_axis is passed (shard_map) — SURVEY §2.4 EP row.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    #: Weight of the Switch/GShard load-balancing auxiliary loss.
    moe_aux_weight: float = 0.01
    #: RMSNorm epsilon (HF rms_norm_eps; Llama-2 ships 1e-5).
    norm_eps: float = 1e-6
    #: Attention QKV projection biases (Qwen2-family; Llama has none).
    attn_bias: bool = False
    #: RoPE frequency scaling: None, or the tuple
    #: (kind, factor, low_freq_factor, high_freq_factor, original_max)
    #: — kind "linear" (position interpolation) or "llama3"
    #: (Llama-3.1 piecewise; see ops/norms.py rope_frequencies).
    #: A tuple (not a dict) so the frozen config stays hashable for
    #: jit static args.
    rope_scaling: Any = None
    # ---- Gemma-family knobs ----
    #: Per-head dimension when it is NOT dim//n_heads (Gemma-2B:
    #: dim 2048, 8 heads, head_dim 256). 0 = derived.
    custom_head_dim: int = 0
    #: GLU gate activation: "silu" (Llama/Qwen/Mistral SwiGLU),
    #: "gelu_tanh" (Gemma GeGLU, torch tanh approximation) or
    #: "gelu_exact" (erf — what transformers uses when a config says
    #: plain "gelu").
    act: str = "silu"
    #: RMSNorm scales by (1 + w) instead of w (Gemma stores w around
    #: zero; applying it Llama-style silently zeroes activations).
    norm_offset: bool = False
    #: Multiply embedding output by sqrt(dim) (Gemma normalizer).
    embed_scale: bool = False
    #: Per-head RMSNorm on q and k before RoPE (Qwen3 family).
    qk_norm: bool = False

    @property
    def head_dim(self) -> int:
        return self.custom_head_dim or self.dim // self.n_heads

    def num_params(self) -> int:
        embed = self.vocab_size * self.dim
        if self.moe_experts:
            ffn = self.dim * self.moe_experts + (
                2 * self.moe_experts * self.dim * self.intermediate
            )  # router + per-expert in/out
        else:
            ffn = 3 * self.dim * self.intermediate  # w1, w2, w3
        per_layer = (
            self.dim * self.n_heads * self.head_dim  # wq
            + 2 * self.dim * self.n_kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * self.dim  # wo
            + ffn
            + 2 * self.dim  # norms
        )
        if self.attn_bias:
            per_layer += (
                self.n_heads + 2 * self.n_kv_heads
            ) * self.head_dim
        if self.qk_norm:
            per_layer += 2 * self.head_dim
        return embed * 2 + self.n_layers * per_layer + self.dim

    # ---- presets ----
    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
            intermediate=128, max_seq_len=128, dtype=jnp.float32, **kw
        )

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        """reference parity target: Llama-2-7B (BASELINE.json configs)."""
        return LlamaConfig(**kw)

    @staticmethod
    def gemma_2b(**kw) -> "LlamaConfig":
        """Gemma-1 2B geometry: GeGLU, (1+w) norms, sqrt(dim) embed
        scale, head_dim decoupled from dim/n_heads."""
        return LlamaConfig(
            vocab_size=256000, dim=2048, n_layers=18, n_heads=8,
            n_kv_heads=1, intermediate=16384, custom_head_dim=256,
            act="gelu_tanh", norm_offset=True, embed_scale=True,
            **kw
        )

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, intermediate=14336, rope_theta=500000.0,
            max_seq_len=8192, **kw
        )

    @staticmethod
    def bench_410m(**kw) -> "LlamaConfig":
        """GPT-medium-scale config for single-chip benchmarking.

        TPU-shaped: head_dim=128 (8 heads) fills the 128-wide MXU
        lanes and halves the softmax VPU work per attention FLOP vs
        the GPT-medium-standard 16x64 split — same param count, same
        flagship (Llama-7B-class) head geometry."""
        return LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=24, n_heads=8,
            n_kv_heads=8, intermediate=2816, max_seq_len=2048, **kw
        )


def model_norm(cfg: LlamaConfig, x, weight):
    """RMSNorm with the family's scale convention — shared by the
    training layer and the KV-cache serving layer so the two can't
    diverge (Gemma scales by 1+w; Llama-family by w)."""
    return rms_norm(
        x, weight, eps=cfg.norm_eps, offset=1.0 if cfg.norm_offset else 0.0
    )


def model_glu(cfg: LlamaConfig, x, gate):
    """GLU with the family's gate activation: act(gate) * x."""
    if cfg.act == "silu":
        return swiglu(x, gate)
    if cfg.act == "gelu_tanh":
        return jax.nn.gelu(gate, approximate=True) * x
    if cfg.act == "gelu_exact":
        return jax.nn.gelu(gate, approximate=False) * x
    raise ValueError(f"unknown activation {cfg.act!r}")


def embed_tokens(cfg: LlamaConfig, params, tokens):
    """Embedding lookup (+ Gemma's sqrt(dim) normalizer, applied in
    the embedding dtype to match transformers' rounding)."""
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.dim), cfg.dtype)
    return x


def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict[str, Any]:
    """Random initialization, layers stacked on axis 0."""
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    dt = cfg.dtype
    hd = cfg.head_dim

    def norm_init(key, fan_in, shape):
        return (
            jax.random.normal(key, shape, jnp.float32)
            * (1.0 / math.sqrt(fan_in))
        ).astype(dt)

    keys = jax.random.split(k_layers, 7)
    L = cfg.n_layers
    layers = {
        "wq": norm_init(keys[0], cfg.dim, (L, cfg.dim, cfg.n_heads * hd)),
        "wk": norm_init(keys[1], cfg.dim, (L, cfg.dim, cfg.n_kv_heads * hd)),
        "wv": norm_init(keys[2], cfg.dim, (L, cfg.dim, cfg.n_kv_heads * hd)),
        "wo": norm_init(keys[3], cfg.n_heads * hd, (L, cfg.n_heads * hd, cfg.dim)),
        "attn_norm": jnp.ones((L, cfg.dim), dt),
        "mlp_norm": jnp.ones((L, cfg.dim), dt),
    }
    if cfg.attn_bias:
        layers.update({
            "bq": jnp.zeros((L, cfg.n_heads * hd), dt),
            "bk": jnp.zeros((L, cfg.n_kv_heads * hd), dt),
            "bv": jnp.zeros((L, cfg.n_kv_heads * hd), dt),
        })
    if cfg.qk_norm:
        layers.update({
            "q_norm": jnp.ones((L, hd), dt),
            "k_norm": jnp.ones((L, hd), dt),
        })
    if cfg.moe_experts:
        E = cfg.moe_experts
        layers.update({
            "router": norm_init(keys[4], cfg.dim, (L, cfg.dim, E)),
            "w_in": norm_init(
                keys[5], cfg.dim, (L, E, cfg.dim, cfg.intermediate)
            ),
            "w_out": norm_init(
                keys[6], cfg.intermediate,
                (L, E, cfg.intermediate, cfg.dim),
            ),
        })
    else:
        layers.update({
            "w1": norm_init(keys[4], cfg.dim, (L, cfg.dim, cfg.intermediate)),
            "w3": norm_init(keys[5], cfg.dim, (L, cfg.dim, cfg.intermediate)),
            "w2": norm_init(keys[6], cfg.intermediate, (L, cfg.intermediate, cfg.dim)),
        })
    return {
        "embed": norm_init(k_embed, cfg.dim, (cfg.vocab_size, cfg.dim)),
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), dt),
        "lm_head": norm_init(k_out, cfg.dim, (cfg.dim, cfg.vocab_size)),
    }


def param_annotations(cfg: LlamaConfig) -> Dict[str, Any]:
    """Logical-axis annotations matching init_params' tree: GSPMD maps
    these through PARAM_RULES (fsdp shards embed dims, tp shards
    heads/mlp/vocab)."""
    layers = {
        "wq": annotate("layers", "embed", "heads"),
        "wk": annotate("layers", "embed", "kv_heads"),
        "wv": annotate("layers", "embed", "kv_heads"),
        "wo": annotate("layers", "heads", "embed"),
        "attn_norm": annotate("layers", None),
        "mlp_norm": annotate("layers", None),
    }
    if cfg.attn_bias:
        layers.update({
            "bq": annotate("layers", "heads"),
            "bk": annotate("layers", "kv_heads"),
            "bv": annotate("layers", "kv_heads"),
        })
    if cfg.qk_norm:
        layers.update({
            "q_norm": annotate("layers", None),
            "k_norm": annotate("layers", None),
        })
    if cfg.moe_experts:
        layers.update({
            "router": annotate("layers", "embed", None),
            "w_in": annotate("layers", "expert", "embed", "mlp"),
            "w_out": annotate("layers", "expert", "mlp", "embed"),
        })
    else:
        layers.update({
            "w1": annotate("layers", "embed", "mlp"),
            "w3": annotate("layers", "embed", "mlp"),
            "w2": annotate("layers", "mlp", "embed"),
        })
    return {
        "embed": annotate("vocab", "embed"),
        "layers": layers,
        "final_norm": annotate(None),
        "lm_head": annotate("embed", "vocab"),
    }


def project_qkv(cfg: LlamaConfig, h, layer):
    """Shared QKV projection (+ Qwen2-family biases) and head split —
    the training layer and the KV-cache serving layer must use the
    SAME projection or their logits silently diverge.
    h: [b, t, dim] -> each of q/k/v: [b, heads, t, head_dim]."""
    b, t, _ = h.shape
    hd = cfg.head_dim
    q, k, v = h @ layer["wq"], h @ layer["wk"], h @ layer["wv"]
    if cfg.attn_bias:
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    q = q.reshape(b, t, cfg.n_heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, t, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, t, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
    if cfg.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim, BEFORE RoPE (callers
        # apply rope to whatever this returns, matching transformers'
        # q_norm/k_norm placement).
        q = rms_norm(q, layer["q_norm"], eps=cfg.norm_eps)
        k = rms_norm(k, layer["k_norm"], eps=cfg.norm_eps)
    return q, k, v


def _attention(cfg: LlamaConfig, q, k, v, sp_axis: Optional[str]):
    k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
    v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)
    if cfg.attention == "ring" and sp_axis is not None:
        return ring_attention(q, k, v, sp_axis, causal=True)
    if cfg.attention == "flash":
        return flash_attention(q, k, v, causal=True)
    return mha_reference(q, k, v, causal=True)


def _layer(cfg: LlamaConfig, x, layer, cos, sin, sp_axis=None,
           ep_axis=None):
    """One decoder block. x: [batch, seq, dim]. Returns (x, aux) where
    aux is the MoE load-balancing loss (0 for dense layers)."""
    b, t, _ = x.shape
    hd = cfg.head_dim
    h = model_norm(cfg, x, layer["attn_norm"])
    q, k, v = project_qkv(cfg, h, layer)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    attn = _attention(cfg, q, k, v, sp_axis)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, cfg.n_heads * hd)
    x = x + attn @ layer["wo"]
    h = model_norm(cfg, x, layer["mlp_norm"])
    if cfg.moe_experts:
        moe_params = {
            "router": layer["router"],
            "w_in": layer["w_in"],
            "w_out": layer["w_out"],
        }
        flat = h.reshape(b * t, -1)
        if ep_axis is not None:
            out, aux = moe_ffn_ep(
                moe_params, flat, axis_name=ep_axis,
                k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
            )
        else:
            out, aux = moe_ffn_dense(moe_params, flat, k=cfg.moe_top_k)
        x = x + out.reshape(b, t, -1)
    else:
        x = x + model_glu(cfg, h @ layer["w1"], h @ layer["w3"]) @ layer["w2"]
        aux = jnp.zeros((), jnp.float32)
    return x, aux


def forward_and_aux(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    sp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
) -> tuple:
    """Token ids [batch, seq] → (logits [batch, seq, vocab] f32,
    aux: summed MoE load-balancing loss, 0 for dense models).

    With sequence parallelism, `tokens` is the local seq shard and
    `positions` carries its global positions.
    """
    b, t = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    x = embed_tokens(cfg, params, tokens)
    cos, sin = rotary_embedding(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    def body(x, layer):
        return _layer(cfg, x, layer, cos, sin, sp_axis, ep_axis)

    if cfg.remat:
        if cfg.remat_policy == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "dots_flash":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        "flash_out", "flash_lse"
                    ),
                ),
            )
        else:
            body = jax.checkpoint(body)
    x, auxs = jax.lax.scan(body, x, params["layers"])
    x = model_norm(cfg, x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, jnp.sum(auxs)


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    sp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
) -> jax.Array:
    """Token ids [batch, seq] → logits [batch, seq, vocab] (f32)."""
    return forward_and_aux(
        params, tokens, cfg, positions=positions, sp_axis=sp_axis,
        ep_axis=ep_axis,
    )[0]


def masked_xent(logits: jax.Array, targets: jax.Array) -> tuple:
    """Masked next-token cross-entropy pieces: (sum_nll, token_count).
    `targets` < 0 are masked out. Returned unreduced so data-parallel
    callers can psum both before dividing."""
    mask = (targets >= 0).astype(jnp.float32)
    safe_targets = jnp.maximum(targets, 0)
    # logsumexp-minus-gather rather than log_softmax-then-gather:
    # identical value, but it never materializes the full [*, vocab]
    # log-probability tensor (2 GiB of f32 HBM traffic per direction
    # at bench shapes).
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(
        logits, safe_targets[..., None], axis=-1
    )[..., 0]
    return jnp.sum((lse - tgt) * mask), jnp.sum(mask)


def loss_fn(
    params: Dict[str, Any],
    tokens: jax.Array,
    targets: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    sp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
) -> jax.Array:
    """Mean next-token cross-entropy (+ weighted MoE aux loss).
    `targets` < 0 are masked out."""
    logits, aux = forward_and_aux(
        params, tokens, cfg, positions=positions, sp_axis=sp_axis,
        ep_axis=ep_axis,
    )
    nll_sum, count = masked_xent(logits, targets)
    xent = nll_sum / jnp.maximum(count, 1.0)
    return xent + cfg.moe_aux_weight * aux


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token (fwd+bwd), standard 6N + attention term —
    used for MFU accounting in bench.py. For MoE, N counts only the
    parameters a token activates (top-k experts, not all E)."""
    n = cfg.num_params()
    if cfg.moe_experts:
        inactive = (cfg.moe_experts - cfg.moe_top_k) * 2 * (
            cfg.dim * cfg.intermediate
        )
        n -= cfg.n_layers * max(inactive, 0)
    # QK^T + AV over n_heads*head_dim total attention width — equal to
    # dim for Llama-family, decoupled for Gemma-style geometries.
    attn_width = cfg.n_heads * cfg.head_dim
    attn = 12 * cfg.n_layers * attn_width * seq_len
    return 6.0 * n + attn / 2  # causal factor 1/2 on the attn term
