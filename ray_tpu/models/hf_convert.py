"""HuggingFace checkpoint conversion (Llama + Qwen2 + Qwen3 +
Mistral + Gemma + Phi-3 families).

The integration-parity role of the reference's framework adapters
(reference: python/ray/train/huggingface/ — Ray Train wraps HF
Trainer/accelerate; SURVEY §2.3 Train-integrations row): here the
integration is TPU-first — convert an HF `LlamaForCausalLM`,
`Qwen2ForCausalLM`, `Qwen3ForCausalLM`, `MistralForCausalLM`,
`GemmaForCausalLM` or `Phi3ForCausalLM` state dict into this
framework's stacked-scan parameter pytree and run it on the
JAX/Pallas stack. All six share a skeleton (RMSNorm, gated MLP,
rotate-half RoPE, GQA); Qwen2 adds QKV projection biases
(cfg.attn_bias); Mistral converts only with its sliding window
disabled (v0.3+ checkpoints — an active window would change
long-context numerics); Gemma-1 swaps in a GeGLU gate, (1+w)
RMSNorms, a sqrt(dim) embedding scale and a head_dim decoupled from
dim/n_heads (gemma-2's soft-capping stays loudly unsupported);
Phi-3 fuses qkv_proj and gate_up_proj, which the converter splits by
output-row ranges; Qwen3 adds per-head RMSNorm on q and k before
RoPE (cfg.qk_norm) with a decoupled head_dim.
tests/test_hf_parity.py proves numerical parity of the full forward
(logits) against transformers' reference implementation for all six.

Weight-layout notes (torch Linear stores [out, in]; we store [in, out]
so activations right-multiply):
  q/k/v/o_proj.weight.T     -> wq/wk/wv/wo
  gate_proj.weight.T        -> w3   (our swiglu(x, gate) gates arg 2)
  up_proj.weight.T          -> w1
  down_proj.weight.T        -> w2
  embed_tokens.weight       -> embed           [vocab, dim]
  lm_head.weight.T          -> lm_head         [dim, vocab]
RoPE uses the same half-split (rotate_half) convention as HF; RMSNorm
eps maps from hf_config.rms_norm_eps (Llama-2 ships 1e-5);
rope_scaling types "llama3" (Llama-3.1+) and "linear" convert with
matching frequency scaling (ops/norms.py rope_frequencies).
Checkpoints carrying tensors with no slot here (o_proj biases,
yarn/dynamic rope variants) fail the conversion loudly instead of
converting into a numerically different model.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from .llama import LlamaConfig


def config_from_hf(hf_config) -> LlamaConfig:
    """Map a transformers LlamaConfig/Qwen2Config onto ours. Raises on
    HF features this model doesn't implement (silent drops would
    convert cleanly and generate subtly wrong logits)."""
    import jax.numpy as jnp

    scaling = getattr(hf_config, "rope_scaling", None)
    rope_scaling = None
    if scaling:
        kind = scaling.get("rope_type", scaling.get("type"))
        if kind in (None, "default"):
            pass
        elif kind == "llama3":
            # Llama-3.1+ piecewise frequency scaling; numerics match
            # HF modeling_rope_utils._compute_llama3_parameters
            # (tests/test_hf_parity.py asserts logit parity).
            rope_scaling = (
                "llama3",
                float(scaling["factor"]),
                float(scaling.get("low_freq_factor", 1.0)),
                float(scaling.get("high_freq_factor", 4.0)),
                int(scaling["original_max_position_embeddings"]),
            )
        elif kind == "linear":
            rope_scaling = (
                "linear", float(scaling["factor"]), 1.0, 4.0, 0
            )
        else:
            raise NotImplementedError(
                f"rope_scaling type {kind!r} is not implemented "
                "(yarn/dynamic/longrope need their own numerics "
                "audit); converting anyway would mis-position every "
                "token"
            )
    model_type = getattr(hf_config, "model_type", "llama")
    if model_type not in (
        "llama", "qwen2", "mistral", "gemma", "phi3", "qwen3"
    ):
        raise NotImplementedError(
            f"model_type={model_type!r}: only the llama, qwen2, "
            "qwen3, mistral, gemma and phi3 families convert; "
            "anything else would need its own numerics audit "
            "(gemma2's logit soft-capping and alternating sliding "
            "windows are NOT implemented — converting one would "
            "silently change its numerics)"
        )
    # Qwen2 gates SWA behind use_sliding_window (default False);
    # Mistral/Phi-3 enable it whenever sliding_window is set AND
    # smaller than the context (Phi-3.5 ships window >= context — a
    # no-op window that must not block conversion; Mistral v0.1's
    # 4096 < 32768 is active and must). An *active* window changes
    # long-context numerics this model doesn't implement.
    window = getattr(hf_config, "sliding_window", None)
    max_pos = getattr(hf_config, "max_position_embeddings", 4096)
    if getattr(hf_config, "use_sliding_window", False) or (
        model_type in ("mistral", "phi3")
        and window is not None
        and window < max_pos
    ):
        raise NotImplementedError(
            "active sliding-window attention is not implemented; "
            "converting would silently change long-context numerics"
        )
    if float(getattr(hf_config, "partial_rotary_factor", 1.0)) != 1.0:
        raise NotImplementedError(
            "partial_rotary_factor != 1.0 (Phi-4-style partial RoPE) "
            "is not implemented; converting would mis-position every "
            "token"
        )
    # Qwen2 carries QKV biases (and only those). Llama's rare
    # attention_bias=True variant ALSO biases o_proj — a layout this
    # model has no slot for, so it stays loudly unsupported. Scoped to
    # llama: a Qwen2 config.json carrying a (redundant)
    # attention_bias key must not trip a Llama-specific guard.
    if model_type == "llama" and getattr(
        hf_config, "attention_bias", False
    ):
        raise NotImplementedError(
            "llama attention_bias=True (biases on all four attention "
            "projections incl. o_proj) is unsupported; qwen2-style "
            "QKV-only biases are the supported biased layout"
        )
    # Gemma family: GeGLU gate, (1+w) norms, sqrt(dim) embedding
    # scale, head_dim decoupled from dim/n_heads, always-tied lm_head.
    act = "silu"
    if model_type == "gemma":
        # transformers' GemmaMLP reads ACT2FN[config.hidden_act]; the
        # separate hidden_activation field is stored but UNUSED by the
        # layer — parity means following hidden_act, and a checkpoint
        # where the two disagree is ambiguous (the 2024-era workaround
        # configs) and must fail loudly, not silently pick one.
        mapping = {"gelu_pytorch_tanh": "gelu_tanh", "gelu": "gelu_exact"}
        hidden_act = getattr(
            hf_config, "hidden_act", "gelu_pytorch_tanh"
        ) or "gelu_pytorch_tanh"
        legacy = getattr(hf_config, "hidden_activation", None)
        if hidden_act not in mapping:
            raise NotImplementedError(
                f"gemma hidden_act={hidden_act!r} unsupported"
            )
        if legacy is not None and legacy != hidden_act:
            raise NotImplementedError(
                f"gemma config carries conflicting activations "
                f"(hidden_act={hidden_act!r}, "
                f"hidden_activation={legacy!r}); converting would "
                "silently diverge from transformers, which uses "
                "hidden_act only"
            )
        act = mapping[hidden_act]
    head_dim = getattr(hf_config, "head_dim", 0) or 0
    if head_dim and head_dim * hf_config.num_attention_heads == (
        hf_config.hidden_size
    ):
        head_dim = 0  # derived — keep the config canonical
    return LlamaConfig(
        attn_bias=model_type == "qwen2",
        qk_norm=model_type == "qwen3",
        custom_head_dim=head_dim,
        act=act,
        norm_offset=model_type == "gemma",
        embed_scale=model_type == "gemma",
        norm_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(
            hf_config, "num_key_value_heads",
            hf_config.num_attention_heads,
        ),
        intermediate=hf_config.intermediate_size,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rope_scaling=rope_scaling,
        max_seq_len=getattr(
            hf_config, "max_position_embeddings", 4096
        ),
        dtype=jnp.float32,
        attention="reference",
        remat=False,
    )


def _np(tensor) -> np.ndarray:
    # .float() first: torch bf16 tensors don't expose .numpy().
    return np.asarray(
        tensor.detach().cpu().float().numpy(), dtype=np.float32
    )


def convert_hf_llama(state_dict: Dict[str, Any], cfg: LlamaConfig):
    """HF LlamaForCausalLM / Qwen2ForCausalLM state dict -> our params
    pytree (layers stacked on axis 0 for lax.scan)."""
    import jax.numpy as jnp

    L = cfg.n_layers
    consumed = set()

    def layer_key(i: int, name: str) -> str:
        return f"model.layers.{i}.{name}"

    def stack(name: str, transpose: bool = True):
        mats = []
        for i in range(L):
            key = layer_key(i, name)
            consumed.add(key)
            w = _np(state_dict[key])
            mats.append(w.T if transpose else w)
        return jnp.asarray(np.stack(mats), dtype=cfg.dtype)

    def split_fused(name: str, boundaries):
        """Split a FUSED projection (Phi-3 qkv_proj / gate_up_proj)
        along its OUTPUT axis at `boundaries`, via the same stack()
        loader ([L, in, out] after transpose). The boundaries must
        cover the matrix exactly — silently dropped rows would
        convert into a numerically wrong model with every shape
        self-consistent."""
        whole = stack(name)
        if whole.shape[-1] != boundaries[-1]:
            raise ValueError(
                f"{name}: fused width {whole.shape[-1]} != expected "
                f"{boundaries[-1]} from the config's head/intermediate "
                "geometry — refusing to convert a partial split"
            )
        out, lo = [], 0
        for hi in boundaries:
            out.append(whole[..., lo:hi])
            lo = hi
        return out

    hd = cfg.head_dim
    fused = layer_key(0, "self_attn.qkv_proj.weight") in state_dict
    if fused:  # Phi-3 layout
        q_rows = cfg.n_heads * hd
        kv_rows = cfg.n_kv_heads * hd
        wq, wk, wv = split_fused(
            "self_attn.qkv_proj.weight",
            [q_rows, q_rows + kv_rows, q_rows + 2 * kv_rows],
        )
        # gate_up_proj fuses [gate; up]; our forward computes
        # glu(h @ w1, h @ w3) with the gate in w3.
        w3, w1 = split_fused(
            "mlp.gate_up_proj.weight",
            [cfg.intermediate, 2 * cfg.intermediate],
        )
        layers = {"wq": wq, "wk": wk, "wv": wv, "w3": w3, "w1": w1}
    else:
        layers = {
            "wq": stack("self_attn.q_proj.weight"),
            "wk": stack("self_attn.k_proj.weight"),
            "wv": stack("self_attn.v_proj.weight"),
            # Our swiglu(x, gate) gates its SECOND argument; the
            # forward computes swiglu(h @ w1, h @ w3), so gate_proj
            # lands in w3.
            "w3": stack("mlp.gate_proj.weight"),
            "w1": stack("mlp.up_proj.weight"),
        }
    layers.update({
        "wo": stack("self_attn.o_proj.weight"),
        "w2": stack("mlp.down_proj.weight"),
        "attn_norm": stack("input_layernorm.weight", transpose=False),
        "mlp_norm": stack(
            "post_attention_layernorm.weight", transpose=False
        ),
    })
    if cfg.attn_bias:  # Qwen2-family QKV biases (1-D: no transpose)
        layers.update({
            "bq": stack("self_attn.q_proj.bias", transpose=False),
            "bk": stack("self_attn.k_proj.bias", transpose=False),
            "bv": stack("self_attn.v_proj.bias", transpose=False),
        })
    if cfg.qk_norm:  # Qwen3 per-head q/k RMSNorm weights
        layers.update({
            "q_norm": stack("self_attn.q_norm.weight", transpose=False),
            "k_norm": stack("self_attn.k_norm.weight", transpose=False),
        })
    embed = _np(state_dict["model.embed_tokens.weight"])
    consumed.add("model.embed_tokens.weight")
    if "lm_head.weight" in state_dict:
        lm_head = _np(state_dict["lm_head.weight"]).T
        consumed.add("lm_head.weight")
    else:  # tied embeddings
        lm_head = embed.T
    consumed.add("model.norm.weight")
    # Every weight must be accounted for: a checkpoint with tensors we
    # don't map (attention/MLP biases, adapters) would otherwise
    # convert silently into a numerically different model.
    leftover = [
        k for k in state_dict
        if k not in consumed
        and not k.endswith("rotary_emb.inv_freq")  # derived buffer
    ]
    if leftover:
        raise ValueError(
            f"unconverted checkpoint tensors {leftover[:8]}"
            f"{'...' if len(leftover) > 8 else ''} — this model has no "
            "slot for them (e.g. attention_bias=True is unsupported)"
        )
    return {
        "embed": jnp.asarray(embed, dtype=cfg.dtype),
        "layers": layers,
        "final_norm": jnp.asarray(
            _np(state_dict["model.norm.weight"]), dtype=cfg.dtype
        ),
        "lm_head": jnp.asarray(lm_head, dtype=cfg.dtype),
    }


def load_hf_llama(model) -> Tuple[Dict[str, Any], LlamaConfig]:
    """From a live transformers LlamaForCausalLM/Qwen2ForCausalLM (or
    a local path loadable by AutoModelForCausalLM — this hermetic
    environment has no model hub access, so paths must be local)."""
    if isinstance(model, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(model)
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    return params, cfg
