"""Public API surface (reference: python/ray/_private/worker.py —
init:1270, get:2663, put:2799, wait:2864, get_actor:3010, kill:3045,
cancel:3076, remote:3253; exports python/ray/__init__.py:175)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from . import exceptions as exc
from ._private.ids import ActorID
from ._private.node import Session
from ._private.worker import global_worker
from .actor import ActorClass, ActorHandle
from .object_ref import ObjectRef
from .remote_function import RemoteFunction

_session: Optional[Session] = None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    _system_config: Optional[dict] = None,
) -> Session:
    """Start (or connect to) a cluster and register this process as a
    driver."""
    global _session
    if _session is not None:
        if ignore_reinit_error:
            return _session
        raise exc.RayTpuError(
            "ray_tpu.init() already called; pass ignore_reinit_error=True "
            "or call shutdown() first."
        )
    import os as _os

    if address is None:
        # Jobs submitted to a running cluster connect via the address
        # the job manager injected (reference: RAY_ADDRESS).
        address = _os.environ.get("RT_ADDRESS") or None
    _session = Session(
        num_cpus=num_cpus,
        num_tpus=num_tpus,
        resources=resources,
        system_config=_system_config,
        address=address,
    )
    # Session-scoped namespace: the default for named-actor creation,
    # get_actor, and list_named_actors (reference: ray.init(namespace)).
    # Propagated to workers through the task/actor spec (ns_ctx in
    # _private/worker.py), so calls inside tasks/actors resolve against
    # THIS namespace too; namespace= stays available as an explicit
    # override everywhere.
    _session.worker.namespace = namespace
    return _session


def shutdown() -> None:
    global _session
    if _session is not None:
        # Stop the metrics flusher BEFORE the session dies: it gets a
        # final flush against a live worker, and the singleton reset
        # means a later re-init binds a fresh buffer (the old flusher
        # thread would otherwise outlive this session and silently
        # throw records at a dead worker forever).
        from .util.metrics import _shutdown_buffer

        _shutdown_buffer()
        _session.shutdown()
        _session = None


def is_initialized() -> bool:
    return _session is not None


def _worker():
    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


def remote(*args, **options):
    """Decorator turning a function into a RemoteFunction or a class
    into an ActorClass. Supports bare `@remote` and
    `@remote(num_cpus=..., num_tpus=..., resources=..., num_returns=...,
    max_retries=..., name=..., max_restarts=...)`.

    Option keys are validated against the shared key universe
    (`_private/options.py` — the same table `ray_tpu check` enforces
    statically): an unknown key raises ValueError naming the bad key
    and the valid set, instead of being silently ignored."""
    if len(args) == 1 and not options and callable(args[0]):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("remote() takes keyword options only")

    def wrapper(obj):
        return _make_remote(obj, options)

    return wrapper


def _make_remote(obj, options):
    if isinstance(obj, type):
        return ActorClass(obj, options)
    return RemoteFunction(obj, options)


def put(value: Any) -> ObjectRef:
    return _worker().put(value)


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    worker = _worker()
    if isinstance(refs, ObjectRef):
        return worker.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects ObjectRef or list, got {type(refs)}")
    return worker.get(list(refs), timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _worker().wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _worker().call(
        "kill_actor",
        actor_id=actor.actor_id.binary(),
        no_restart=no_restart,
    )


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    _worker().call("cancel_task", task_id=ref.id().task_id().binary())


def get_actor(
    name: str, namespace: Optional[str] = None
) -> ActorHandle:
    if namespace is None:
        namespace = _worker().namespace
    reply = _worker().call(
        "get_named_actor", name=name, namespace=namespace
    )
    if not reply.get("found"):
        raise ValueError(f"Actor {name!r} not found in namespace {namespace!r}")
    return ActorHandle(ActorID(reply["actor_id"]), reply["handle_meta"] or {})


def cluster_resources() -> Dict[str, float]:
    return _worker().call("cluster_resources")["resources"]


def available_resources() -> Dict[str, float]:
    return _worker().call("available_resources")["resources"]


def nodes() -> List[dict]:
    return _worker().call("list_nodes")["nodes"]


def timeline() -> List[dict]:
    """Task state-transition events (reference: GcsTaskManager ring
    buffer serving `ray.timeline` / the state API)."""
    return _worker().call("list_task_events")["events"]


def state_summary() -> dict:
    return _worker().call("state_summary")["summary"]


def diagnose(
    *,
    hung_task_s: Optional[float] = None,
    straggler_threshold: Optional[float] = None,
    capture_stacks: bool = True,
    leak_age_s: Optional[float] = None,
    locality_miss_threshold: Optional[float] = None,
) -> dict:
    """Stall doctor: one verdict over head task state, per-worker
    in-flight views, step telemetry, and flight-recorder digests —
    stragglers (worker median step time > cluster p50 × threshold),
    hung tasks (in flight past the deadline, stack auto-captured via
    the profile relay), unresponsive workers, dead nodes — plus
    `verdict.memory`: nodes near arena capacity, object-leak
    suspects held past `leak_age_s` by dead owners, and spill
    thrash — plus `verdict.locks`: observed lock-order inversion
    cycles and held-while-blocking sites from every process running
    the lock witness (`RT_lock_witness_enabled=1`; each cycle is
    also a `lock_order_inversion` problem, so the doctor's exit
    code covers deadlock risk). The CLI surface is
    `ray_tpu doctor`; thresholds default
    to the cluster config (`doctor_hung_task_s`,
    `doctor_straggler_threshold`, `doctor_leak_age_s`) — plus
    `verdict.data`: the hottest cross-node flow from the transfer
    matrix, pull- vs restore-dominated classification per job, and
    misplaced-task suspects (task classes pulling most of their get
    bytes from a node that had capacity to run them;
    `doctor_locality_miss_threshold` sets the conviction bar)."""
    kwargs: Dict[str, Any] = {"capture_stacks": capture_stacks}
    if hung_task_s is not None:
        kwargs["hung_task_s"] = float(hung_task_s)
    if straggler_threshold is not None:
        kwargs["straggler_threshold"] = float(straggler_threshold)
    if leak_age_s is not None:
        kwargs["leak_age_s"] = float(leak_age_s)
    if locality_miss_threshold is not None:
        kwargs["locality_miss_threshold"] = float(
            locality_miss_threshold
        )
    # Step records may still sit in this process's metrics buffer.
    # Best-effort: a doctor run against a sick cluster must not die
    # on the flush that the verdict would have explained.
    from .util.metrics import flush_best_effort

    flush_best_effort()
    return _worker().call("diagnose", timeout=120.0, **kwargs)[
        "verdict"
    ]


def profile_gang(
    job_id: Optional[str] = None,
    *,
    duration_s: float = 2.0,
    hz: float = 100.0,
    path: Optional[str] = None,
) -> dict:
    """Coordinated gang profiling: one synchronized profiler window
    across every rank of a gang, merged — with the gang's
    step-telemetry phases — into one chrome trace on a shared clock
    (see `ray_tpu.util.state.profile_gang`; CLI:
    ``ray_tpu profile --job``)."""
    from .util.state import profile_gang as _profile_gang

    return _profile_gang(
        job_id, duration_s=duration_s, hz=hz, path=path
    )


class RuntimeContext:
    """Execution-context introspection (reference:
    python/ray/runtime_context.py:30 RuntimeContext — get_job_id /
    get_node_id / get_task_id / get_actor_id / get_worker_id /
    get_accelerator_ids via ray.get_runtime_context())."""

    def __init__(self, worker):
        self._worker = worker

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_node_id(self) -> str:
        return self._worker.node_id.hex()

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        """Id of the task this code runs inside; None on a driver."""
        task_id = getattr(self._worker._ctx, "task_id", None)
        if task_id is None:
            # Async actor methods run on the shared event-loop thread,
            # where identity rides a (asyncio-task-local) contextvar.
            from ._private.worker import _ASYNC_TASK_ID

            task_id = _ASYNC_TASK_ID.get()
        return task_id.hex() if task_id is not None else None

    def get_actor_id(self) -> Optional[str]:
        """Id of the actor this code runs inside; None elsewhere."""
        actor_id = self._worker._actor_id
        return actor_id.hex() if actor_id is not None else None

    def get_accelerator_ids(self) -> Dict[str, List[str]]:
        """Accelerator ids visible to THIS worker (reference:
        RuntimeContext.get_accelerator_ids; TPU chip visibility rides
        TPU_VISIBLE_CHIPS, accelerators/tpu.py)."""
        import os as _os

        chips = _os.environ.get("TPU_VISIBLE_CHIPS", "")
        return {"TPU": [c for c in chips.split(",") if c]}


def get_runtime_context() -> RuntimeContext:
    """The context of the current driver/task/actor (reference:
    python/ray/runtime_context.py:520 get_runtime_context)."""
    return RuntimeContext(_worker())
