"""Actor classes and handles (reference: python/ray/actor.py —
ActorClass._remote:890 registers with the control plane and submits the
creation task; ActorMethod._remote:314 submits ordered actor tasks;
handles are serializable and resolve through the actor table)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ._private.ids import ActorID


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        from ._private.options import validate_options

        self._cls = cls
        self._options = dict(options or {})
        # Decorator and .options() clones both construct through here:
        # unknown keys raise with the valid key set instead of being
        # silently merged (the RT102 bug class, enforced at runtime).
        validate_options("actor", self._options)
        self._exported_key: Optional[str] = None
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actors cannot be instantiated directly; use "
            f"{self._cls.__name__}.remote()."
        )

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        clone = ActorClass(self._cls, merged)
        clone._exported_key = self._exported_key
        return clone

    def remote(self, *args, **kwargs) -> "ActorHandle":
        from ._private.api_internal import create_actor

        return create_actor(self, args, kwargs)

    @property
    def underlying(self) -> type:
        return self._cls

    @property
    def actor_options(self) -> Dict[str, Any]:
        return self._options

    def method_names(self) -> list:
        return [
            name
            for name in dir(self._cls)
            if callable(getattr(self._cls, name)) and not name.startswith("__")
        ]


def method(**options):
    """Per-method default options, applied at class-definition time
    (reference: python/ray/actor.py ray.method — num_returns and
    concurrency_group annotations)::

        @rt.remote(concurrency_groups={"io": 2})
        class A:
            @rt.method(concurrency_group="io")
            def fetch(self): ...
    """
    allowed = {"num_returns", "concurrency_group"}
    unknown = set(options) - allowed
    if unknown:
        raise ValueError(f"unknown method options: {sorted(unknown)}")

    def decorator(fn):
        fn.__rt_method_options__ = options
        return fn

    return decorator


class ActorMethod:
    def __init__(
        self,
        handle: "ActorHandle",
        name: str,
        num_returns: int = 1,
        concurrency_group: Optional[str] = None,
    ):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(
        self,
        num_returns: Optional[int] = None,
        concurrency_group: Optional[str] = None,
        **_ignored,
    ) -> "ActorMethod":
        # None = keep this method's current value (which may carry an
        # @rt.method definition-time default) — overriding one option
        # must not silently reset the other.
        return ActorMethod(
            self._handle,
            self._name,
            self._num_returns if num_returns is None else num_returns,
            concurrency_group
            if concurrency_group is not None
            else self._concurrency_group,
        )

    def remote(self, *args, **kwargs):
        from ._private.api_internal import submit_actor_method

        return submit_actor_method(
            self._handle,
            self._name,
            args,
            kwargs,
            self._num_returns,
            concurrency_group=self._concurrency_group,
        )

    def bind(self, *args, **kwargs):
        """Lazy DAG node for this method call (reference:
        python/ray/dag/class_node.py)."""
        from .dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    """Serializable reference to a live actor."""

    def __init__(self, actor_id: ActorID, meta: Dict[str, Any]):
        self._actor_id = actor_id
        self._meta = meta  # {"class_name", "methods": [...]}

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        methods = self._meta.get("methods")
        if methods is not None and name not in methods:
            raise AttributeError(
                f"Actor {self._meta.get('class_name', '?')} has no "
                f"method {name!r}"
            )
        defaults = (self._meta.get("method_defaults") or {}).get(name, {})
        return ActorMethod(
            self,
            name,
            num_returns=defaults.get("num_returns", 1),
            concurrency_group=defaults.get("concurrency_group"),
        )

    def __repr__(self):
        return (
            f"ActorHandle({self._meta.get('class_name', '?')}, "
            f"{self._actor_id.hex()})"
        )

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._meta))
