"""Autoscaling test cluster.

Reference: python/ray/cluster_utils.py:26 AutoscalingCluster — a head
plus a FakeMultiNodeProvider-backed autoscaler, so elasticity tests
run hermetically on one machine.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..cluster_utils import Cluster
from .autoscaler import Monitor, NodeTypeConfig, StandardAutoscaler
from .node_provider import FakeMultiNodeProvider


class AutoscalingCluster:
    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        worker_node_types: Optional[Dict[str, dict]] = None,
        idle_timeout_s: float = 3.0,
        update_interval_s: float = 0.3,
    ):
        self.cluster = Cluster(
            initialize_head=True,
            head_resources=head_resources or {"CPU": 1.0},
        )
        types = {
            name: NodeTypeConfig(
                resources=spec["resources"],
                min_workers=spec.get("min_workers", 0),
                max_workers=spec.get("max_workers", 4),
                labels=spec.get("labels", {}),
            )
            for name, spec in (worker_node_types or {}).items()
        }
        self.provider = FakeMultiNodeProvider(
            self.cluster.address, self.cluster.session_dir
        )
        self.autoscaler = StandardAutoscaler(
            self.provider, types, idle_timeout_s=idle_timeout_s
        )
        self.monitor = Monitor(self.autoscaler, update_interval_s)

    @property
    def address(self) -> str:
        return self.cluster.address

    def start(self) -> None:
        self.monitor.start()

    def num_workers(self) -> int:
        return len(self.provider.non_terminated_nodes())

    def shutdown(self) -> None:
        self.monitor.stop()
        self.provider.shutdown()
        self.cluster.shutdown()
