"""Autoscaling test cluster.

Reference: python/ray/cluster_utils.py:26 AutoscalingCluster — a head
plus a FakeMultiNodeProvider-backed autoscaler, so elasticity tests
run hermetically on one machine.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..cluster_utils import Cluster
from .autoscaler import Monitor, NodeTypeConfig, StandardAutoscaler
from .node_provider import FakeMultiNodeProvider


class AutoscalingCluster:
    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        worker_node_types: Optional[Dict[str, dict]] = None,
        idle_timeout_s: float = 3.0,
        update_interval_s: float = 0.3,
    ):
        self.cluster = Cluster(
            initialize_head=True,
            head_resources=head_resources or {"CPU": 1.0},
        )
        types = {
            name: NodeTypeConfig(
                resources=spec["resources"],
                min_workers=spec.get("min_workers", 0),
                max_workers=spec.get("max_workers", 4),
                labels=spec.get("labels", {}),
            )
            for name, spec in (worker_node_types or {}).items()
        }
        self.provider = FakeMultiNodeProvider(
            self.cluster.address, self.cluster.session_dir
        )
        self.autoscaler = StandardAutoscaler(
            self.provider, types, idle_timeout_s=idle_timeout_s
        )
        self.monitor = Monitor(self.autoscaler, update_interval_s)

    @property
    def address(self) -> str:
        return self.cluster.address

    def start(self) -> None:
        self.monitor.start()

    def num_workers(self) -> int:
        return len(self.provider.non_terminated_nodes())

    def shutdown(self) -> None:
        self.monitor.stop()
        self.provider.shutdown()
        self.cluster.shutdown()


class TpuAutoscalingCluster:
    """A head plus a GcpTpuNodeProvider driven against the in-memory
    fake TPU API — the hermetic test double for slice-granular
    autoscaling (reference: the GCP provider's unit tests stub the
    googleapiclient HTTP layer the same way). Production swaps the
    fake transport for the default RestTransport; everything above the
    transport (client, provider, autoscaler) is the code under test.

    `tpu_node_types` example::

        {"tpu-v5e-16": {"pod_type": "v5e-16",
                        "accelerator_type": "v5litepod-16",
                        "max_workers": 2, "host_cpus": 2.0}}
    """

    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        tpu_node_types: Optional[Dict[str, dict]] = None,
        idle_timeout_s: float = 3.0,
        update_interval_s: float = 0.3,
    ):
        from .._private.accelerators.tpu import (
            chips_per_host,
            pod_worker_count,
        )
        from .gcp import (
            FakeGcpTpuService,
            GcpTpuNodeProvider,
        )
        from .gcp.node_provider import FakeSliceHostBooter

        self.cluster = Cluster(
            initialize_head=True,
            head_resources=head_resources or {"CPU": 1.0},
        )
        tpu_node_types = tpu_node_types or {}
        self.booter = FakeSliceHostBooter(
            self.cluster.address,
            self.cluster.session_dir,
            tpu_node_types=tpu_node_types,
        )
        self.service = FakeGcpTpuService(
            project="fake-project",
            zone="fake-zone-a",
            on_node_ready=self.booter.node_ready,
            on_node_deleted=self.booter.node_deleted,
        )
        self.provider = GcpTpuNodeProvider(
            self.cluster.address,
            project="fake-project",
            zone="fake-zone-a",
            cluster_name="rt-test",
            tpu_node_types=tpu_node_types,
            transport=self.service,
        )
        types = {}
        for name, spec in tpu_node_types.items():
            pod_type = spec["pod_type"]
            types[name] = NodeTypeConfig(
                resources={
                    "CPU": float(spec.get("host_cpus", 2.0)),
                    "TPU": float(chips_per_host(pod_type)),
                    "memory": float(2**30),
                },
                min_workers=spec.get("min_workers", 0),
                max_workers=spec.get("max_workers", 2),
                slice_hosts=pod_worker_count(pod_type),
            )
        self.autoscaler = StandardAutoscaler(
            self.provider, types, idle_timeout_s=idle_timeout_s
        )
        self.monitor = Monitor(self.autoscaler, update_interval_s)

    @property
    def address(self) -> str:
        return self.cluster.address

    def start(self) -> None:
        self.monitor.start()

    def num_slices(self) -> int:
        return len(self.provider.non_terminated_nodes())

    def shutdown(self) -> None:
        self.monitor.stop()
        self.provider.shutdown()
        self.booter.shutdown()
        self.cluster.shutdown()
