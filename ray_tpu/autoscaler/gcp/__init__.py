"""GCE/GKE TPU node provider.

Reference: python/ray/autoscaler/_private/gcp/ — node_provider.py
(GCPNodeProvider), node.py:629 (GCPTPU REST resource, GCPNodeType.TPU),
tpu_command_runner.py (per-host fan-out). The tpu-native redesign keeps
the same cloud surface (TPU v2 REST API: nodes.create/list/get/delete +
operations.get) but treats a pod SLICE as the atomic scaling unit: one
provider node = one slice = N host daemons that join the cluster with
pod-head resources, so a pending `slice_placement_group` maps to
exactly one node request.
"""

from .api import FakeGcpTpuService, GcpTpuClient
from .node_provider import GcpTpuNodeProvider

__all__ = [
    "FakeGcpTpuService",
    "GcpTpuClient",
    "GcpTpuNodeProvider",
]
