"""TPU v2 REST API client (tpu.googleapis.com).

Reference: python/ray/autoscaler/_private/gcp/node.py:629 GCPTPU — the
paths and verbs this client speaks are the same ones the reference
drives through googleapiclient: `projects/{p}/locations/{zone}/nodes`
create/list/get/delete and `.../operations/{id}` polling. We speak them
directly over a pluggable transport instead of the discovery client, so
tests inject `FakeGcpTpuService` (a recorded-responses in-memory
service) and exercise every byte of the client code path; production
uses the urllib transport with an OAuth bearer token.

Node body (TPU VM API):
    {"acceleratorType": "v5litepod-16", "runtimeVersion": "...",
     "networkConfig": {"enableExternalIps": true},
     "metadata": {"startup-script": "..."}, "labels": {...}}
Node response adds: name, state (CREATING/READY/DELETING/...), and
networkEndpoints (one per slice host).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

#: transport(method, path, body, params) -> response dict.
Transport = Callable[[str, str, Optional[dict], Optional[dict]], dict]

API_ROOT = "https://tpu.googleapis.com/v2"


class GcpApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"TPU API {status}: {message}")
        self.status = status


class RestTransport:
    """Production transport: JSON over HTTPS with a bearer token from
    GOOGLE_TPU_API_TOKEN (tests/CI) or the GCE metadata server (on-VM;
    only attempted at call time — zero-egress environments never block
    at import)."""

    def __init__(self, timeout_s: float = 30.0):
        self.timeout_s = timeout_s
        self._token: Optional[str] = None

    def _bearer(self) -> str:
        if self._token:
            return self._token
        token = os.environ.get("GOOGLE_TPU_API_TOKEN")
        if not token:
            import urllib.request

            req = urllib.request.Request(
                "http://metadata.google.internal/computeMetadata/v1/"
                "instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                token = json.loads(resp.read())["access_token"]
        self._token = token
        return token

    def __call__(self, method, path, body=None, params=None) -> dict:
        import urllib.error
        import urllib.parse
        import urllib.request

        url = f"{API_ROOT}/{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={
                "Authorization": f"Bearer {self._bearer()}",
                "Content-Type": "application/json",
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise GcpApiError(e.code, e.read().decode(errors="replace"))


class GcpTpuClient:
    """Thin typed wrapper over the TPU node REST surface."""

    def __init__(
        self,
        project: str,
        zone: str,
        transport: Optional[Transport] = None,
        poll_interval_s: float = 1.0,
    ):
        self.project = project
        self.zone = zone
        self.transport = transport or RestTransport()
        self.poll_interval_s = poll_interval_s

    @property
    def parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def create_node(self, node_id: str, body: dict) -> dict:
        """Submit slice creation; returns the long-running operation
        (reference: GCPTPU.create_instance nodes.create)."""
        return self.transport(
            "POST", f"{self.parent}/nodes", body, {"nodeId": node_id}
        )

    def list_nodes(self) -> List[dict]:
        out = self.transport("GET", f"{self.parent}/nodes", None, None)
        return out.get("nodes", [])

    def get_node(self, name: str) -> dict:
        return self.transport("GET", name, None, None)

    def delete_node(self, name: str) -> dict:
        return self.transport("DELETE", name, None, None)

    def get_operation(self, name: str) -> dict:
        return self.transport("GET", name, None, None)

    def wait_for_operation(self, operation: dict, timeout_s=300.0) -> dict:
        """Poll until done (reference: GCPTPU.wait_for_operation)."""
        deadline = time.monotonic() + timeout_s
        op = operation
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TimeoutError(f"operation {op.get('name')} timed out")
            time.sleep(self.poll_interval_s)
            op = self.get_operation(op["name"])
        if "error" in op:
            raise GcpApiError(500, str(op["error"]))
        return op


class FakeGcpTpuService:
    """In-memory TPU API double with recorded-response semantics.

    Serves the same paths/verbs as tpu.googleapis.com so GcpTpuClient
    runs unmodified (reference test model: the autoscaler's GCP tests
    stub googleapiclient at the HTTP layer). Creation is asynchronous
    like the real service: the operation completes after `ready_delay_s`
    and the node transitions CREATING -> READY; at that transition the
    fake "runs the startup script" — the `on_node_ready` hook boots the
    slice's host daemons in-process the way cloud-init would on each
    TPU VM host.
    """

    def __init__(
        self,
        project: str = "proj",
        zone: str = "fake-zone-a",
        ready_delay_s: float = 0.05,
        on_node_ready: Optional[Callable[[str, dict], None]] = None,
        on_node_deleted: Optional[Callable[[str], None]] = None,
    ):
        self.project = project
        self.zone = zone
        self.ready_delay_s = ready_delay_s
        self.on_node_ready = on_node_ready
        self.on_node_deleted = on_node_deleted
        self._lock = threading.Lock()
        self._nodes: Dict[str, dict] = {}  # full name -> node body
        self._ops: Dict[str, dict] = {}
        self.request_log: List[tuple] = []

    @property
    def parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    # -- transport entrypoint -----------------------------------------
    def __call__(self, method, path, body=None, params=None) -> dict:
        with self._lock:
            self.request_log.append((method, path))
        if method == "POST" and path == f"{self.parent}/nodes":
            return self._create(params["nodeId"], body)
        if method == "GET" and path == f"{self.parent}/nodes":
            with self._lock:
                return {"nodes": [dict(n) for n in self._nodes.values()]}
        if method == "GET" and "/operations/" in path:
            return self._get_op(path)
        if method == "GET":
            with self._lock:
                node = self._nodes.get(path)
            if node is None:
                raise GcpApiError(404, f"node {path} not found")
            return dict(node)
        if method == "DELETE":
            return self._delete(path)
        raise GcpApiError(400, f"unhandled {method} {path}")

    # -- handlers ------------------------------------------------------
    def _create(self, node_id: str, body: dict) -> dict:
        name = f"{self.parent}/nodes/{node_id}"
        with self._lock:
            if name in self._nodes:
                raise GcpApiError(409, f"node {node_id} exists")
            node = dict(body)
            node["name"] = name
            node["state"] = "CREATING"
            self._nodes[name] = node
            op_name = f"{self.parent}/operations/{uuid.uuid4().hex[:8]}"
            self._ops[op_name] = {"name": op_name, "done": False}
        timer = threading.Timer(
            self.ready_delay_s, self._make_ready, (name, op_name)
        )
        timer.daemon = True
        timer.start()
        return {"name": op_name, "done": False}

    def _make_ready(self, name: str, op_name: str) -> None:
        with self._lock:
            node = self._nodes.get(name)
            if node is None or node["state"] != "CREATING":
                return
            node["state"] = "READY"
            # One endpoint per slice host, like the real API.
            hosts = int(node.get("metadata", {}).get("rt-slice-hosts", 1))
            node["networkEndpoints"] = [
                {"ipAddress": f"10.0.0.{i + 1}"} for i in range(hosts)
            ]
            self._ops[op_name] = {
                "name": op_name,
                "done": True,
                "response": {"name": name},
            }
            hook = self.on_node_ready
        if hook is not None:
            hook(name, dict(node))

    def _get_op(self, path: str) -> dict:
        with self._lock:
            op = self._ops.get(path)
        if op is None:
            raise GcpApiError(404, f"operation {path} not found")
        return dict(op)

    def _delete(self, path: str) -> dict:
        with self._lock:
            node = self._nodes.pop(path, None)
            hook = self.on_node_deleted
        if node is None:
            raise GcpApiError(404, f"node {path} not found")
        if hook is not None:
            hook(path)  # the fake's "VM teardown": daemons die with it
        return {"name": f"{path}/operations/delete", "done": True}
