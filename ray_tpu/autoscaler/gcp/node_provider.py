"""GCE TPU node provider: one provider node == one pod slice.

Reference: python/ray/autoscaler/_private/gcp/node_provider.py
(GCPNodeProvider) + node.py:108 (GCPNodeType.TPU routes node names to
the TPU API) + tpu_command_runner.py (the reference reaches every slice
host over SSH). TPU-native redesign: instead of a command runner
fanning out to hosts, each TPU VM host boots its own daemon from the
node's startup script (cloud-init), tagged with the provider-node
label; the autoscaler then maps N joined daemons back to this one
provider node. Scale-up granularity is the SLICE — the autoscaler
launches one node per pending `slice_placement_group`, never partial
slices.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from ..._private.accelerators.tpu import chips_per_host, pod_worker_count
from ..node_provider import NodeProvider
from .api import FakeGcpTpuService, GcpApiError, GcpTpuClient

#: Label keys on the cloud node (GCP label values must be lowercase;
#: these mirror the reference's ray-cluster-name / ray-node-type tags).
LABEL_CLUSTER = "rt-cluster-name"
LABEL_NODE_TYPE = "rt-node-type"

#: Label key the joined daemons carry (cluster side, free-form).
PROVIDER_NODE_LABEL = "rt.io/provider-node"


def _startup_script(head_address: str, provider_node: str) -> str:
    """The per-host boot script baked into node metadata. Every TPU VM
    host of the slice runs it (reference: the GCP provider's
    startup-script metadata; TPU_WORKER_ID etc. are provided by the
    TPU VM environment and picked up by accelerator detection)."""
    labels = json.dumps({PROVIDER_NODE_LABEL: provider_node})
    return (
        "#!/bin/bash\n"
        f"python -m ray_tpu start --address={head_address} "
        f"--labels='{labels}' "
        "--listen-host=$(hostname -I | awk '{print $1}')\n"
    )


class GcpTpuNodeProvider(NodeProvider):
    """Drives the TPU v2 REST surface through GcpTpuClient.

    `tpu_node_types` maps autoscaler node-type names to their cloud
    shape::

        {"tpu-v5e-16": {"pod_type": "v5e-16",
                        "accelerator_type": "v5litepod-16",
                        "runtime_version": "tpu-ubuntu2204-base"}}

    Creation is asynchronous (the cloud operation completes in the
    background; CREATING nodes count as launching capacity). The
    provider never blocks the reconcile loop on cloud latency.
    """

    def __init__(
        self,
        head_address: str,
        *,
        project: str,
        zone: str,
        cluster_name: str,
        tpu_node_types: Dict[str, dict],
        transport=None,
    ):
        super().__init__(head_address)
        self.cluster_name = cluster_name
        self.tpu_node_types = tpu_node_types
        self.client = GcpTpuClient(
            project, zone, transport=transport, poll_interval_s=0.05
        )
        self._lock = threading.Lock()
        self._seq = 0
        # short node id -> rt-node-type, refreshed by every
        # list_nodes; node_type() reads it instead of issuing one
        # nodes.get per node per reconcile tick (an N+1 REST-call
        # pattern against data the list already carried).
        self._type_cache: Dict[str, str] = {}

    # -- capacity shape ------------------------------------------------
    def slice_hosts(self, node_type: str) -> int:
        spec = self.tpu_node_types.get(node_type)
        if not spec:
            return 1
        return pod_worker_count(spec["pod_type"])

    def host_chips(self, node_type: str) -> int:
        spec = self.tpu_node_types.get(node_type)
        if not spec:
            return 0
        return chips_per_host(spec["pod_type"])

    # -- NodeProvider surface ------------------------------------------
    def create_node(self, node_type, resources, labels) -> str:
        spec = self.tpu_node_types[node_type]
        with self._lock:
            self._seq += 1
            short = f"{self.cluster_name}-{node_type}-{self._seq}-tpu"
        body = {
            "acceleratorType": spec["accelerator_type"],
            "runtimeVersion": spec.get(
                "runtime_version", "tpu-ubuntu2204-base"
            ),
            "networkConfig": {"enableExternalIps": True},
            "labels": {
                LABEL_CLUSTER: self.cluster_name,
                LABEL_NODE_TYPE: node_type,
                **{
                    str(k).lower(): str(v).lower()
                    for k, v in (labels or {}).items()
                },
            },
            "metadata": {
                "startup-script": _startup_script(
                    self.head_address, short
                ),
                "rt-slice-hosts": str(self.slice_hosts(node_type)),
            },
        }
        # Fire-and-track: nodes.create returns a long-running
        # operation; the node lists as CREATING until the service
        # finishes (reference: create_instance(wait_for_operation=
        # False) path).
        self.client.create_node(short, body)
        return short

    def _full_name(self, short: str) -> str:
        return f"{self.client.parent}/nodes/{short}"

    def terminate_node(self, node_id: str) -> None:
        try:
            self.client.delete_node(self._full_name(node_id))
        except GcpApiError as e:
            if e.status != 404:
                raise

    def _cluster_nodes(self) -> List[dict]:
        nodes = [
            n
            for n in self.client.list_nodes()
            if n.get("labels", {}).get(LABEL_CLUSTER) == self.cluster_name
            and n.get("state") not in ("DELETING", "TERMINATED")
        ]
        with self._lock:
            self._type_cache = {
                n["name"].rsplit("/", 1)[1]: n.get("labels", {}).get(
                    LABEL_NODE_TYPE
                )
                for n in nodes
            }
        return nodes

    def non_terminated_nodes(self) -> List[str]:
        return [n["name"].rsplit("/", 1)[1] for n in self._cluster_nodes()]

    def node_type(self, node_id: str) -> Optional[str]:
        with self._lock:
            cached = self._type_cache.get(node_id)
        if cached is not None:
            return cached
        try:
            node = self.client.get_node(self._full_name(node_id))
        except GcpApiError:
            return None
        return node.get("labels", {}).get(LABEL_NODE_TYPE)

    def cluster_node_id(self, node_id: str) -> Optional[str]:
        """Unused for slice nodes: N daemons map to one provider node
        via the rt.io/provider-node label the autoscaler reads from
        cluster_load (see StandardAutoscaler._nodes_by_provider)."""
        return None

    def provider_node_label(self, node_id: str) -> str:
        return node_id

    def shutdown(self) -> None:
        for node_id in self.non_terminated_nodes():
            try:
                self.terminate_node(node_id)
            except GcpApiError:
                pass


class FakeSliceHostBooter:
    """Plays the role of cloud-init on a fake TPU slice: when the fake
    service marks a node READY, boot one in-process NodeDaemon per
    slice host with exactly the resources/labels the accelerator
    manager would detect on a real TPU VM host (reference test model:
    fake_multi_node/node_provider.py boots real raylets; here the
    hosts additionally carry pod-head + pod-name slice resources,
    accelerators/tpu.py get_extra_resources_and_labels)."""

    def __init__(
        self,
        head_address: str,
        session_root: str,
        *,
        host_cpus: float = 2.0,
        tpu_node_types: Optional[Dict[str, dict]] = None,
    ):
        self.head_address = head_address
        self.session_root = session_root
        self.host_cpus = host_cpus
        self.tpu_node_types = tpu_node_types or {}
        self._lock = threading.Lock()
        self._daemons: Dict[str, list] = {}

    def node_ready(self, name: str, node: dict) -> None:
        import os

        from ..._private.config import Config
        from ..._private.daemon import NodeDaemon

        short = name.rsplit("/", 1)[1]
        node_type = node.get("labels", {}).get(LABEL_NODE_TYPE, "")
        spec = self.tpu_node_types.get(node_type, {})
        pod_type = spec.get("pod_type", "v5e-4")
        hosts = pod_worker_count(pod_type)
        per_host = chips_per_host(pod_type)
        booted = []
        for worker_id in range(hosts):
            resources = {
                "CPU": self.host_cpus,
                "TPU": float(per_host),
                "memory": float(2**30),
                # Every host advertises the pod-name resource; host 0
                # adds the slice-head marker (accelerators/tpu.py
                # get_extra_resources_and_labels, reference tpu.py:334).
                short: 1.0,
            }
            if worker_id == 0:
                resources[f"TPU-{pod_type}-head"] = 1.0
            labels = {
                PROVIDER_NODE_LABEL: short,
                "rt.io/tpu-pod-type": pod_type,
                "rt.io/tpu-pod-name": short,
                "rt.io/tpu-worker-id": str(worker_id),
            }
            daemon = NodeDaemon(
                os.path.join(self.session_root, f"{short}-w{worker_id}"),
                resources,
                Config.from_env(None),
                is_head=False,
                head_address=self.head_address,
                labels=labels,
            )
            daemon.start()
            booted.append(daemon)
        with self._lock:
            self._daemons[short] = booted

    def node_deleted(self, name: str) -> None:
        short = name.rsplit("/", 1)[1]
        with self._lock:
            booted = self._daemons.pop(short, [])
        for daemon in booted:
            try:
                daemon.shutdown()
            except Exception:
                pass

    def shutdown(self) -> None:
        with self._lock:
            all_daemons = [
                d for ds in self._daemons.values() for d in ds
            ]
            self._daemons.clear()
        for daemon in all_daemons:
            try:
                daemon.shutdown()
            except Exception:
                pass
