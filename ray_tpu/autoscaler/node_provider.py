"""NodeProvider plugin interface.

Reference: python/ray/autoscaler/node_provider.py — the cloud-agnostic
surface the autoscaler drives (create/terminate/list); concrete
providers plug in per platform (GCE TPU pods being the one that
matters here). FakeMultiNodeProvider boots real in-process worker
daemons, the keystone test double (reference:
autoscaler/_private/fake_multi_node/node_provider.py).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional


class NodeProvider:
    """One node = one opaque node_id string."""

    def __init__(self, head_address: str):
        self.head_address = head_address

    def create_node(
        self, node_type: str, resources: Dict[str, float], labels: Dict
    ) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type(self, node_id: str) -> Optional[str]:
        raise NotImplementedError

    def cluster_node_id(self, node_id: str) -> Optional[str]:
        """Provider node id -> cluster node id (hex) once registered."""
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class FakeMultiNodeProvider(NodeProvider):
    """Launches worker NodeDaemons inside this process."""

    def __init__(self, head_address: str, session_root: str):
        super().__init__(head_address)
        self.session_root = session_root
        self._lock = threading.Lock()
        self._nodes: Dict[str, dict] = {}
        self._seq = 0

    def create_node(self, node_type, resources, labels) -> str:
        from .._private.config import Config
        from .._private.daemon import NodeDaemon

        with self._lock:
            self._seq += 1
            provider_id = f"fake-{node_type}-{self._seq}"
        daemon = NodeDaemon(
            os.path.join(self.session_root, provider_id),
            dict(resources),
            Config.from_env(None),
            is_head=False,
            head_address=self.head_address,
            labels=dict(labels or {}),
        )
        daemon.start()
        with self._lock:
            self._nodes[provider_id] = {
                "daemon": daemon,
                "type": node_type,
            }
        return provider_id

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(node_id, None)
        if node is not None:
            node["daemon"].shutdown()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def node_type(self, node_id: str) -> Optional[str]:
        with self._lock:
            node = self._nodes.get(node_id)
            return node["type"] if node else None

    def cluster_node_id(self, node_id: str) -> Optional[str]:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return None
            return node["daemon"].node_id.hex()

    def shutdown(self) -> None:
        for node_id in self.non_terminated_nodes():
            self.terminate_node(node_id)
