"""Autoscaler v2: instance-manager redesign.

Reference: python/ray/autoscaler/v2/ — v1 counts provider nodes and
reacts; v2 tracks every cloud instance through an explicit lifecycle
state machine (instance_manager/common.py InstanceUtil transition
table), stores versioned instance records (instance_storage.py), and
drives everything from one declarative `Reconciler.reconcile()` pass
(instance_manager/reconciler.py) that diffs desired state against the
cloud provider's and the cluster's reported reality.
"""

from .autoscaler import AutoscalerV2, AutoscalingClusterV2, MonitorV2
from .instance import Instance, InstanceStatus
from .instance_manager import InstanceManager
from .reconciler import Reconciler

__all__ = [
    "AutoscalerV2",
    "AutoscalingClusterV2",
    "MonitorV2",
    "Instance",
    "InstanceStatus",
    "InstanceManager",
    "Reconciler",
]
