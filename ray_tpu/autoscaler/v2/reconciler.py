"""Declarative reconcile pass.

Reference: python/ray/autoscaler/v2/instance_manager/reconciler.py —
one idempotent function diffs three sources of truth (the instance
table, the cloud provider's non-terminated list, the cluster's
reported node states) and emits InstanceUpdateEvents:

  passive transitions (sync with observed reality)
    REQUESTED  -> ALLOCATED          cloud instance appeared
    REQUESTED  -> QUEUED / ALLOCATION_FAILED   launch timeout or error
    ALLOCATED  -> RAY_RUNNING        daemon(s) registered with head
    ALLOCATED  -> RAY_INSTALL_FAILED boot timeout
    RAY_RUNNING-> RAY_STOPPED        daemons vanished from head view
    *          -> TERMINATED         cloud instance vanished
    TERMINATING-> TERMINATION_FAILED terminate call failed (retried)

  active transitions (make reality match demand)
    new QUEUED instances             unmet demand / min_workers floor
    QUEUED     -> REQUESTED          launch slot available
    RAY_RUNNING-> RAY_STOP_REQUESTED idle past timeout, above floor
    RAY_STOPPING / RAY_STOPPED -> TERMINATING
    leaked cloud instances           terminated directly

Slice granularity carries over from v1: one instance whose node type
has slice_hosts > 1 is a whole TPU pod slice; gangs launch one slice,
idle checks require every host daemon idle, termination kills the
whole slice.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..autoscaler import (
    PROVIDER_NODE_LABEL,
    NodeTypeConfig,
    _consume,
    _fits,
)
from .instance import ACTIVE_STATUSES, Instance, InstanceStatus as S
from .instance_manager import InstanceManager, InstanceUpdateEvent


@dataclass
class ReconcileConfig:
    #: REQUESTED older than this retries (or fails permanently).
    request_timeout_s: float = 30.0
    max_launch_attempts: int = 3
    #: ALLOCATED / RAY_INSTALLING older than this is a failed boot.
    install_timeout_s: float = 120.0
    idle_timeout_s: float = 5.0
    max_concurrent_requests: int = 8


@dataclass
class ProviderError:
    """Launch/terminate failure surfaced by the cloud provider."""

    kind: str  # "launch" | "terminate"
    instance_id: Optional[str] = None
    cloud_instance_id: Optional[str] = None
    details: str = ""


@dataclass
class CloudInstance:
    cloud_instance_id: str
    instance_type: str
    #: Launch tag: which Instance requested this cloud node.
    instance_id: Optional[str] = None


class Reconciler:
    """Stateless; everything it needs arrives as arguments."""

    @staticmethod
    def reconcile(
        manager: InstanceManager,
        *,
        node_types: Dict[str, NodeTypeConfig],
        cloud_instances: Dict[str, CloudInstance],
        load: dict,
        config: ReconcileConfig,
        provider_errors: Optional[List[ProviderError]] = None,
        node_ids_of=None,
    ) -> dict:
        """One pass. Applies events through `manager` (versioned) and
        returns {"events": n, "leaked": [cloud ids], "demand": n}.

        `load` is the head's cluster_load payload (nodes / infeasible /
        pending_placement_groups). `node_ids_of(cloud_id) -> [node]`
        maps a cloud instance to its registered daemons; defaults to
        matching the rt.io/provider-node label.
        """
        version, instances = manager.get_state()
        by_id = instances
        events: List[InstanceUpdateEvent] = []
        errors = provider_errors or []
        err_by_instance = {
            e.instance_id: e for e in errors if e.instance_id
        }
        err_by_cloud = {
            e.cloud_instance_id: e
            for e in errors
            if e.cloud_instance_id
        }

        nodes = load.get("nodes", [])

        if node_ids_of is None:

            def node_ids_of(cloud_id: str) -> List[dict]:
                return [
                    n
                    for n in nodes
                    if (n.get("labels") or {}).get(PROVIDER_NODE_LABEL)
                    == cloud_id
                ]

        cloud_by_tag: Dict[str, CloudInstance] = {
            ci.instance_id: ci
            for ci in cloud_instances.values()
            if ci.instance_id
        }
        claimed: Set[str] = {
            inst.cloud_instance_id
            for inst in by_id.values()
            if inst.cloud_instance_id
        }

        # ---- passive: sync instance table with observed reality -----
        for inst in by_id.values():
            if inst.status == S.REQUESTED:
                err = err_by_instance.get(inst.instance_id)
                ci = cloud_by_tag.get(inst.instance_id)
                if ci is not None:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.ALLOCATED,
                            cloud_instance_id=ci.cloud_instance_id,
                            details="cloud instance appeared",
                        )
                    )
                elif err is not None or (
                    inst.seconds_in_status() > config.request_timeout_s
                ):
                    why = (
                        err.details
                        if err
                        else f"launch timeout "
                        f"({config.request_timeout_s}s)"
                    )
                    if (
                        inst.launch_attempts
                        >= config.max_launch_attempts
                    ):
                        events.append(
                            InstanceUpdateEvent(
                                instance_id=inst.instance_id,
                                new_status=S.ALLOCATION_FAILED,
                                details=why,
                            )
                        )
                    else:
                        events.append(
                            InstanceUpdateEvent(
                                instance_id=inst.instance_id,
                                new_status=S.QUEUED,
                                details=f"retrying: {why}",
                            )
                        )
            elif inst.status in (S.ALLOCATED, S.RAY_INSTALLING):
                if inst.cloud_instance_id not in cloud_instances:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATED,
                            details="cloud instance vanished",
                        )
                    )
                    continue
                daemons = node_ids_of(inst.cloud_instance_id)
                if daemons:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.RAY_RUNNING,
                            node_ids=[
                                d["node_id"] for d in daemons
                            ],
                            details=f"{len(daemons)} daemon(s) up",
                        )
                    )
                elif (
                    inst.seconds_in_status()
                    > config.install_timeout_s
                ):
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.RAY_INSTALL_FAILED,
                            details="boot timeout",
                        )
                    )
            elif inst.status == S.RAY_RUNNING:
                if inst.cloud_instance_id not in cloud_instances:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATED,
                            details="cloud instance vanished",
                        )
                    )
                elif not node_ids_of(inst.cloud_instance_id):
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.RAY_STOPPED,
                            details="daemons gone from head view",
                        )
                    )
            elif inst.status == S.RAY_STOP_REQUESTED:
                # The stopper subscriber normally advances this; the
                # passive edge covers daemons dying under the request.
                if inst.cloud_instance_id not in cloud_instances:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATED,
                            details="cloud instance vanished",
                        )
                    )
                elif not node_ids_of(inst.cloud_instance_id):
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.RAY_STOPPED,
                            details="daemons gone from head view",
                        )
                    )
            elif inst.status in (
                S.RAY_STOPPING,
                S.RAY_STOPPED,
                S.RAY_INSTALL_FAILED,
            ):
                if inst.cloud_instance_id not in cloud_instances:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATED,
                            details="cloud instance vanished",
                        )
                    )
                else:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATING,
                            details="reclaiming cloud instance",
                        )
                    )
            elif inst.status == S.TERMINATING:
                err = err_by_cloud.get(inst.cloud_instance_id)
                if inst.cloud_instance_id not in cloud_instances:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATED,
                            details="terminated",
                        )
                    )
                elif err is not None:
                    events.append(
                        InstanceUpdateEvent(
                            instance_id=inst.instance_id,
                            new_status=S.TERMINATION_FAILED,
                            details=err.details,
                        )
                    )
            elif inst.status == S.TERMINATION_FAILED:
                events.append(
                    InstanceUpdateEvent(
                        instance_id=inst.instance_id,
                        new_status=S.TERMINATING,
                        details="retrying terminate",
                    )
                )

        # ---- leaked cloud instances ---------------------------------
        # Unclaimed, and not about to be adopted: only a REQUESTED
        # instance can adopt its tag. A node whose tagged instance
        # already moved on (timed-out retry that later completed, so a
        # SECOND launch got adopted — or the instance failed) must be
        # reclaimed, not orphaned forever.
        leaked = [
            cid
            for cid, ci in cloud_instances.items()
            if cid not in claimed
            and not (
                ci.instance_id is not None
                and ci.instance_id in by_id
                and by_id[ci.instance_id].status == S.REQUESTED
            )
        ]

        # ---- active: scale up ---------------------------------------
        counts: Dict[str, int] = {}
        for inst in by_id.values():
            if inst.is_active():
                counts[inst.instance_type] = (
                    counts.get(inst.instance_type, 0) + 1
                )
        # Account events already emitted this pass that deactivate an
        # instance (vanished cloud nodes etc.) so the floor check
        # relaunches immediately.
        deactivated = {
            ev.instance_id
            for ev in events
            if ev.new_status
            not in ACTIVE_STATUSES | {S.RAY_RUNNING}
            and ev.instance_id
        }
        for iid in deactivated:
            inst = by_id.get(iid)
            if inst is not None and inst.is_active():
                counts[inst.instance_type] = (
                    counts.get(inst.instance_type, 1) - 1
                )

        to_launch: Dict[str, int] = {}
        for name, cfg in node_types.items():
            have = counts.get(name, 0)
            if have < cfg.min_workers:
                to_launch[name] = cfg.min_workers - have

        flat: List[Dict[str, float]] = [
            r for r in load.get("infeasible", []) if r
        ]
        gangs: List[List[Dict[str, float]]] = []
        for pg in load.get("pending_placement_groups", []):
            bundles = [dict(b) for b in pg.get("bundles", []) if b]
            if not bundles:
                continue
            if pg.get("strategy") in ("STRICT_SPREAD", "SPREAD"):
                gangs.append(bundles)
            else:
                flat.extend(bundles)

        # Capacity pool: live daemons' availability + full per-host
        # shape for every active-but-not-yet-registered instance.
        pool: List[Dict[str, float]] = [
            dict(n.get("available", {})) for n in nodes
        ]
        for inst in by_id.values():
            if inst.status in (
                S.QUEUED,
                S.REQUESTED,
                S.ALLOCATED,
                S.RAY_INSTALLING,
            ):
                cfg = node_types.get(inst.instance_type)
                if cfg is not None:
                    pool.extend(
                        dict(cfg.resources)
                        for _ in range(max(1, cfg.slice_hosts))
                    )

        def _room(name: str) -> int:
            cfg = node_types[name]
            return cfg.max_workers - (
                counts.get(name, 0) + to_launch.get(name, 0)
            )

        def _launch_for(request, distinct_needed=1):
            for name, cfg in sorted(
                node_types.items(),
                key=lambda kv: (
                    kv[1].slice_hosts < distinct_needed,
                    kv[1].slice_hosts,
                    kv[0],
                ),
            ):
                if _room(name) <= 0:
                    continue
                if not _fits(request, cfg.resources):
                    continue
                needed = max(
                    1, math.ceil(distinct_needed / cfg.slice_hosts)
                )
                if _room(name) < needed:
                    continue
                to_launch[name] = to_launch.get(name, 0) + needed
                fresh = [
                    dict(cfg.resources)
                    for _ in range(needed * cfg.slice_hosts)
                ]
                pool.extend(fresh)
                return fresh
            return None

        for request in flat:
            for capacity in pool:
                if _fits(request, capacity):
                    _consume(capacity, request)
                    break
            else:
                added = _launch_for(request)
                if added:
                    _consume(added[0], request)

        for bundles in gangs:
            used: set = set()
            unplaced: List[Dict[str, float]] = []
            for request in bundles:
                placed = False
                for idx, capacity in enumerate(pool):
                    if idx in used:
                        continue
                    if _fits(request, capacity):
                        _consume(capacity, request)
                        used.add(idx)
                        placed = True
                        break
                if not placed:
                    unplaced.append(request)
            if unplaced:
                need: Dict[str, float] = {}
                for request in unplaced:
                    for rname, amount in request.items():
                        need[rname] = max(
                            need.get(rname, 0.0), amount
                        )
                added = _launch_for(need, len(unplaced))
                if added:
                    for request, capacity in zip(unplaced, added):
                        _consume(capacity, request)

        for name, n in to_launch.items():
            for _ in range(n):
                events.append(
                    InstanceUpdateEvent(
                        instance_id=None,
                        instance_type=name,
                        new_status=S.QUEUED,
                        details="demand",
                    )
                )

        # ---- active: QUEUED -> REQUESTED (bounded in-flight) --------
        in_flight = sum(
            1
            for i in by_id.values()
            if i.status == S.REQUESTED
        )
        for inst in by_id.values():
            if inst.status != S.QUEUED:
                continue
            if in_flight >= config.max_concurrent_requests:
                break
            events.append(
                InstanceUpdateEvent(
                    instance_id=inst.instance_id,
                    new_status=S.REQUESTED,
                    details="launch slot",
                )
            )
            in_flight += 1

        # ---- active: idle scale-down --------------------------------
        for inst in by_id.values():
            if inst.status != S.RAY_RUNNING:
                continue
            cfg = node_types.get(inst.instance_type)
            if cfg is None:
                continue
            if counts.get(inst.instance_type, 0) <= cfg.min_workers:
                continue
            daemons = node_ids_of(inst.cloud_instance_id)
            if not daemons:
                continue
            busy = any(
                d.get("queued", 0) > 0
                or any(
                    d.get("available", {}).get(k, 0.0) != v
                    for k, v in d.get("total", {}).items()
                )
                for d in daemons
            )
            now = time.time()
            if busy:
                inst.last_busy = now
                continue
            # Idle since whichever is later: last observed busy, or
            # the moment the instance became RAY_RUNNING.
            anchor = max(
                inst.last_busy, inst.history[-1].timestamp
            )
            if now - anchor >= config.idle_timeout_s:
                events.append(
                    InstanceUpdateEvent(
                        instance_id=inst.instance_id,
                        new_status=S.RAY_STOP_REQUESTED,
                        details="idle",
                    )
                )
                counts[inst.instance_type] -= 1

        manager.update(events, expected_version=version)
        return {
            "events": len(events),
            "leaked": leaked,
            "demand": len(flat) + sum(len(g) for g in gangs),
        }
