"""Instance lifecycle state machine.

Reference: python/ray/autoscaler/v2/instance_manager/common.py
(InstanceUtil.get_valid_transitions) — every autoscaled cloud instance
moves through an explicit status graph; transitions outside the table
are bugs, every transition is recorded with a timestamp so stuck
states can be timed out.

Status graph (happy path left-to-right):

  QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOP_REQUESTED
                                                    -> RAY_STOPPING
                                                    -> RAY_STOPPED
                                                    -> TERMINATING
                                                    -> TERMINATED

with failure edges REQUESTED->{QUEUED retry, ALLOCATION_FAILED},
ALLOCATED->RAY_INSTALLING->{RAY_RUNNING, RAY_INSTALL_FAILED}, and
TERMINATING->TERMINATION_FAILED->TERMINATING retry.
"""

from __future__ import annotations

import enum
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set


class InstanceStatus(enum.Enum):
    #: Scheduler decided a new instance is needed; not yet requested.
    QUEUED = "QUEUED"
    #: Launch request sent to the cloud provider.
    REQUESTED = "REQUESTED"
    #: Cloud instance appears in the provider's non-terminated list.
    ALLOCATED = "ALLOCATED"
    #: Framework daemon being installed/booted on the instance.
    RAY_INSTALLING = "RAY_INSTALLING"
    #: Node daemon registered with the head and is schedulable.
    RAY_RUNNING = "RAY_RUNNING"
    #: Autoscaler wants the daemon stopped (idle scale-down).
    RAY_STOP_REQUESTED = "RAY_STOP_REQUESTED"
    #: Daemon draining.
    RAY_STOPPING = "RAY_STOPPING"
    #: Daemon reported dead by the head.
    RAY_STOPPED = "RAY_STOPPED"
    #: Terminate request sent to the cloud provider.
    TERMINATING = "TERMINATING"
    #: Gone from the provider's non-terminated list. Terminal.
    TERMINATED = "TERMINATED"
    #: Provider could not allocate (or timed out repeatedly). Terminal.
    ALLOCATION_FAILED = "ALLOCATION_FAILED"
    #: Daemon failed to boot on an allocated instance. Terminal-ish
    #: (reconciler terminates the cloud instance).
    RAY_INSTALL_FAILED = "RAY_INSTALL_FAILED"
    #: Provider terminate call failed; retried.
    TERMINATION_FAILED = "TERMINATION_FAILED"


S = InstanceStatus

#: Valid transitions (reference: common.py get_valid_transitions).
VALID_TRANSITIONS: Dict[InstanceStatus, Set[InstanceStatus]] = {
    S.QUEUED: {S.REQUESTED},
    S.REQUESTED: {S.ALLOCATED, S.QUEUED, S.ALLOCATION_FAILED},
    S.ALLOCATED: {
        S.RAY_INSTALLING,
        S.RAY_RUNNING,
        S.RAY_STOPPING,
        S.RAY_STOPPED,
        S.TERMINATING,
        S.TERMINATED,
    },
    S.RAY_INSTALLING: {
        S.RAY_RUNNING,
        S.RAY_INSTALL_FAILED,
        S.RAY_STOPPED,
        S.TERMINATING,
        S.TERMINATED,
    },
    S.RAY_RUNNING: {
        S.RAY_STOP_REQUESTED,
        S.RAY_STOPPING,
        S.RAY_STOPPED,
        S.TERMINATING,
        S.TERMINATED,
    },
    S.RAY_STOP_REQUESTED: {
        S.RAY_STOPPING,
        S.RAY_STOPPED,
        S.RAY_RUNNING,  # stop request rejected (node busy again)
        S.TERMINATED,
    },
    S.RAY_STOPPING: {S.RAY_STOPPED, S.TERMINATING, S.TERMINATED},
    S.RAY_STOPPED: {S.TERMINATING, S.TERMINATED},
    S.TERMINATING: {S.TERMINATED, S.TERMINATION_FAILED},
    S.TERMINATION_FAILED: {S.TERMINATING},
    S.TERMINATED: set(),
    S.ALLOCATION_FAILED: set(),
    S.RAY_INSTALL_FAILED: {S.TERMINATING, S.TERMINATED},
}

#: Statuses that count toward a node type's live/launching population
#: (for max_workers accounting and demand netting).
ACTIVE_STATUSES = {
    S.QUEUED,
    S.REQUESTED,
    S.ALLOCATED,
    S.RAY_INSTALLING,
    S.RAY_RUNNING,
}


@dataclass
class StatusTransition:
    status: InstanceStatus
    timestamp: float
    details: str = ""


@dataclass
class Instance:
    instance_type: str
    instance_id: str = field(
        default_factory=lambda: uuid.uuid4().hex[:12]
    )
    status: InstanceStatus = S.QUEUED
    #: Provider-side id once ALLOCATED (opaque; one per instance).
    cloud_instance_id: Optional[str] = None
    #: Cluster node ids of the daemons on this instance once
    #: RAY_RUNNING (a TPU slice instance hosts several daemons).
    node_ids: List[str] = field(default_factory=list)
    launch_attempts: int = 0
    #: Ephemeral bookkeeping (not a state-machine field): last time
    #: the reconciler saw any of this instance's daemons busy.
    last_busy: float = 0.0
    history: List[StatusTransition] = field(default_factory=list)

    def __post_init__(self):
        if not self.history:
            self.history.append(
                StatusTransition(self.status, time.time(), "created")
            )

    def transition(
        self, new_status: InstanceStatus, details: str = ""
    ) -> bool:
        """Apply a transition; False (no mutation) if invalid."""
        if new_status not in VALID_TRANSITIONS[self.status]:
            return False
        self.status = new_status
        self.history.append(
            StatusTransition(new_status, time.time(), details)
        )
        return True

    def seconds_in_status(self) -> float:
        return time.time() - self.history[-1].timestamp

    def is_active(self) -> bool:
        return self.status in ACTIVE_STATUSES

    def summary(self) -> dict:
        return {
            "instance_id": self.instance_id,
            "instance_type": self.instance_type,
            "status": self.status.value,
            "cloud_instance_id": self.cloud_instance_id,
            "node_ids": list(self.node_ids),
            "transitions": [
                {
                    "status": t.status.value,
                    "at": t.timestamp,
                    "details": t.details,
                }
                for t in self.history
            ],
        }
