"""AutoscalerV2: reconciler loop + provider subscribers.

Reference: python/ray/autoscaler/v2/autoscaler.py (wires InstanceManager
+ Reconciler + cloud provider) and instance_manager/subscribers/
{cloud_instance_updater.py, ray_stopper.py} — status transitions drive
side effects: REQUESTED launches on the provider, TERMINATING
terminates, RAY_STOP_REQUESTED drains. Provider calls run on a worker
thread; their failures surface as ProviderErrors consumed by the next
reconcile pass rather than exceptions in the loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

from ..autoscaler import PROVIDER_NODE_LABEL, NodeTypeConfig
from ..node_provider import FakeMultiNodeProvider, NodeProvider
from .instance import Instance, InstanceStatus as S
from .instance_manager import InstanceManager, InstanceUpdateEvent
from .reconciler import (
    CloudInstance,
    ProviderError,
    ReconcileConfig,
    Reconciler,
)


class V1ProviderAdapter:
    """Bridges the v1 NodeProvider plugin surface (synchronous
    create/terminate/list, used by the GCE TPU provider and the fake
    in-process provider) to the v2 async cloud-instance view."""

    def __init__(
        self,
        provider: NodeProvider,
        node_types: Dict[str, NodeTypeConfig],
    ):
        self.provider = provider
        self.node_types = node_types
        self._lock = threading.Lock()
        #: cloud_instance_id -> launch tag (instance_id)
        self._tags: Dict[str, str] = {}
        self._errors: List[ProviderError] = []
        self._work: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._drain, daemon=True
        )
        self._thread.start()

    # -- async ops (worker thread) ------------------------------------
    def _drain(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            kind, payload = item
            try:
                if kind == "launch":
                    inst: Instance = payload
                    cfg = self.node_types[inst.instance_type]
                    cloud_id = self.provider.create_node(
                        inst.instance_type,
                        cfg.resources,
                        dict(cfg.labels),
                    )
                    with self._lock:
                        self._tags[cloud_id] = inst.instance_id
                elif kind == "terminate":
                    self.provider.terminate_node(payload)
            except Exception as e:  # noqa: BLE001 — surfaced as error
                with self._lock:
                    if kind == "launch":
                        self._errors.append(
                            ProviderError(
                                kind="launch",
                                instance_id=payload.instance_id,
                                details=repr(e),
                            )
                        )
                    else:
                        self._errors.append(
                            ProviderError(
                                kind="terminate",
                                cloud_instance_id=payload,
                                details=repr(e),
                            )
                        )

    def launch(self, inst: Instance) -> None:
        self._work.put(("launch", inst))

    def terminate(self, cloud_instance_id: str) -> None:
        self._work.put(("terminate", cloud_instance_id))

    def non_terminated(self) -> Dict[str, CloudInstance]:
        out: Dict[str, CloudInstance] = {}
        with self._lock:
            tags = dict(self._tags)
        for cid in self.provider.non_terminated_nodes():
            out[cid] = CloudInstance(
                cloud_instance_id=cid,
                instance_type=self.provider.node_type(cid) or "",
                instance_id=tags.get(cid),
            )
        return out

    def poll_errors(self) -> List[ProviderError]:
        with self._lock:
            errors, self._errors = self._errors, []
            return errors

    def node_ids_of(self, cloud_id: str, load: dict) -> List[dict]:
        """Daemons of one cloud instance: label match (slice nodes)
        with single-node provider mapping fallback (same resolution as
        v1 StandardAutoscaler._daemons_of)."""
        daemons = [
            n
            for n in load.get("nodes", [])
            if (n.get("labels") or {}).get(PROVIDER_NODE_LABEL)
            == cloud_id
        ]
        if daemons:
            return daemons
        node_id = self.provider.cluster_node_id(cloud_id)
        return [
            n
            for n in load.get("nodes", [])
            if n["node_id"] == node_id
        ]

    def shutdown(self) -> None:
        self._work.put(None)
        self._thread.join(timeout=5)


class AutoscalerV2:
    def __init__(
        self,
        provider: NodeProvider,
        node_types: Dict[str, NodeTypeConfig],
        *,
        head_address: Optional[str] = None,
        config: Optional[ReconcileConfig] = None,
    ):
        self.node_types = node_types
        self.config = config or ReconcileConfig()
        self.adapter = V1ProviderAdapter(provider, node_types)
        self.manager = InstanceManager()
        self.head_address = head_address or provider.head_address
        self._client = None
        #: Two-strike leak reclaim: a cloud id is only terminated if
        #: it was already unclaimed on the PREVIOUS pass — closes the
        #: race where a freshly created node is listed before its
        #: launch tag lands in the adapter.
        self._leak_suspects: set = set()
        self.manager.subscribe(self._on_update)

    # -- subscriber: transitions -> provider side effects -------------
    def _on_update(
        self, inst: Instance, ev: InstanceUpdateEvent
    ) -> None:
        if ev.new_status == S.REQUESTED:
            inst.launch_attempts += 1
            self.adapter.launch(inst)
        elif ev.new_status == S.TERMINATING:
            if inst.cloud_instance_id:
                self.adapter.terminate(inst.cloud_instance_id)
        elif ev.new_status == S.RAY_STOP_REQUESTED:
            # No separate drain protocol on the fake/GCE providers:
            # acknowledge the stop so the reconciler reclaims the
            # cloud instance next pass (RAY_STOPPING -> TERMINATING).
            self.manager.update(
                [
                    InstanceUpdateEvent(
                        instance_id=inst.instance_id,
                        new_status=S.RAY_STOPPING,
                        details="drain acknowledged",
                    )
                ]
            )

    def _load(self) -> dict:
        from ..._private.rpc import RpcClient

        if self._client is None:
            self._client = RpcClient(self.head_address)
        return self._client.call("cluster_load")

    def update(self) -> dict:
        load = self._load()
        cloud = self.adapter.non_terminated()
        result = Reconciler.reconcile(
            self.manager,
            node_types=self.node_types,
            cloud_instances=cloud,
            load=load,
            config=self.config,
            provider_errors=self.adapter.poll_errors(),
            node_ids_of=lambda cid: self.adapter.node_ids_of(
                cid, load
            ),
        )
        # Leaked cloud instances (present at the provider, unknown to
        # the instance table) are reclaimed on the second consecutive
        # sighting.
        leaked_now = set(result["leaked"])
        for cid in leaked_now & self._leak_suspects:
            self.adapter.terminate(cid)
        self._leak_suspects = leaked_now
        return result

    def summary(self) -> List[dict]:
        return self.manager.summary()

    def shutdown(self) -> None:
        self.adapter.shutdown()


class MonitorV2:
    """Background reconcile loop (reference: v2/monitor.py)."""

    def __init__(
        self, autoscaler: AutoscalerV2, interval_s: float = 0.5
    ):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 — loop must survive
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class AutoscalingClusterV2:
    """Hermetic v2 test cluster: head + fake provider + v2 loop
    (v2 twin of autoscaler.cluster.AutoscalingCluster)."""

    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        worker_node_types: Optional[Dict[str, dict]] = None,
        idle_timeout_s: float = 3.0,
        update_interval_s: float = 0.3,
    ):
        from ...cluster_utils import Cluster

        self.cluster = Cluster(
            initialize_head=True,
            head_resources=head_resources or {"CPU": 1.0},
        )
        types = {
            name: NodeTypeConfig(
                resources=spec["resources"],
                min_workers=spec.get("min_workers", 0),
                max_workers=spec.get("max_workers", 4),
                labels=spec.get("labels", {}),
                slice_hosts=spec.get("slice_hosts", 1),
            )
            for name, spec in (worker_node_types or {}).items()
        }
        self.provider = FakeMultiNodeProvider(
            self.cluster.address, self.cluster.session_dir
        )
        self.autoscaler = AutoscalerV2(
            self.provider,
            types,
            config=ReconcileConfig(idle_timeout_s=idle_timeout_s),
        )
        self.monitor = MonitorV2(self.autoscaler, update_interval_s)

    @property
    def address(self) -> str:
        return self.cluster.address

    def start(self) -> None:
        self.monitor.start()

    def num_workers(self) -> int:
        return len(self.provider.non_terminated_nodes())

    def shutdown(self) -> None:
        self.monitor.stop()
        self.autoscaler.shutdown()
        self.provider.shutdown()
        self.cluster.shutdown()
