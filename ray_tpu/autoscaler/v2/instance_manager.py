"""Versioned instance store + subscriber fan-out.

Reference: python/ray/autoscaler/v2/instance_manager/
{instance_storage.py, instance_manager.py} — the instance table is
updated only through versioned batches (optimistic concurrency: an
update carries the version it was computed against and is rejected if
the table moved), and every applied status change is fanned out to
subscribers (CloudInstanceUpdater launches/terminates on the provider,
RayStopper drains nodes) so side effects happen exactly once per
transition.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .instance import Instance, InstanceStatus


@dataclass
class InstanceUpdateEvent:
    """One requested mutation of the instance table."""

    instance_id: Optional[str] = None  # None => new instance
    new_status: Optional[InstanceStatus] = None
    instance_type: Optional[str] = None  # for new instances
    cloud_instance_id: Optional[str] = None
    node_ids: Optional[List[str]] = None
    details: str = ""
    #: Extra payload subscribers may need (e.g. per-host resources for
    #: a launch).
    metadata: dict = field(default_factory=dict)


class InstanceManager:
    """The only writer of the instance table."""

    def __init__(self):
        self._lock = threading.RLock()
        self._instances: Dict[str, Instance] = {}
        self._version = 0
        self._subscribers: List[
            Callable[[Instance, InstanceUpdateEvent], None]
        ] = []

    # -- read ----------------------------------------------------------
    def get_state(self) -> tuple:
        """(version, {instance_id: Instance}) snapshot. Instances are
        the live objects; callers must not mutate them directly."""
        with self._lock:
            return self._version, dict(self._instances)

    def instances(self) -> List[Instance]:
        with self._lock:
            return list(self._instances.values())

    # -- write ---------------------------------------------------------
    def subscribe(
        self, fn: Callable[[Instance, InstanceUpdateEvent], None]
    ) -> None:
        self._subscribers.append(fn)

    def update(
        self,
        updates: List[InstanceUpdateEvent],
        expected_version: Optional[int] = None,
    ) -> bool:
        """Apply a batch. Returns False (nothing applied) when
        expected_version no longer matches — the caller recomputes
        against fresh state, exactly like the reference's
        UpdateInstanceManagerState version check."""
        applied: List[tuple] = []
        with self._lock:
            if (
                expected_version is not None
                and expected_version != self._version
            ):
                return False
            for ev in updates:
                if ev.instance_id is None:
                    inst = Instance(instance_type=ev.instance_type)
                    self._instances[inst.instance_id] = inst
                    applied.append((inst, ev))
                    continue
                inst = self._instances.get(ev.instance_id)
                if inst is None:
                    continue
                if ev.cloud_instance_id is not None:
                    inst.cloud_instance_id = ev.cloud_instance_id
                if ev.node_ids is not None:
                    inst.node_ids = list(ev.node_ids)
                if ev.new_status is not None:
                    if not inst.transition(ev.new_status, ev.details):
                        continue  # invalid edge: drop, don't corrupt
                applied.append((inst, ev))
            if applied:
                self._version += 1
        # Side effects outside the lock: a subscriber may call back
        # into update() (e.g. instant-allocation providers).
        for inst, ev in applied:
            for fn in self._subscribers:
                fn(inst, ev)
        return True

    def summary(self) -> List[dict]:
        with self._lock:
            return [i.summary() for i in self._instances.values()]
