"""Programmatic autoscaler API.

Reference: python/ray/autoscaler/sdk/__init__.py request_resources —
applications command a standing capacity target ("make sure the
cluster can hold this much") independent of any queued work; the
autoscaler scales up to satisfy it and holds the satisfying nodes
against idle scale-down until the target is replaced.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(
    num_cpus: Optional[int] = None,
    bundles: Optional[List[Dict[str, float]]] = None,
) -> int:
    """Set (REPLACE) the cluster's standing resource target.

    `num_cpus=N` expands to N one-CPU bundles (the reference's
    semantics — aggregate CPU capacity, placeable anywhere); N must
    be a non-negative integer (integral floats like `4.0` are
    accepted; `2.5` is an error, never a silent truncation to 2, and
    `num_cpus=0` is an explicit clear). `bundles` is a list of
    resource dicts that must each fit on some node. Calling with
    neither (or `bundles=[]`) clears the target, letting idle nodes
    scale down again. Returns the number of bundles now standing.
    """
    # Argument validation happens BEFORE any cluster traffic (and
    # before the worker lookup): a bad target must never half-apply.
    if num_cpus is not None:
        if isinstance(num_cpus, bool) or not isinstance(
            num_cpus, (int, float)
        ):
            raise TypeError(
                f"num_cpus must be an integer, got "
                f"{type(num_cpus).__name__}"
            )
        if num_cpus < 0:
            raise ValueError(f"num_cpus must be >= 0, got {num_cpus}")
        if isinstance(num_cpus, float) and not num_cpus.is_integer():
            raise ValueError(
                f"num_cpus must be a whole number of CPUs, got "
                f"{num_cpus} (fractional targets are not truncated)"
            )
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    out: List[Dict[str, float]] = []
    if num_cpus:
        out.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    for bundle in bundles or ():
        # Same contract as placement_group(): non-empty
        # {resource: amount > 0} dicts — a zero/negative amount would
        # trivially "fit" every node and pin it against scale-down
        # forever.
        if not isinstance(bundle, dict) or not bundle:
            raise ValueError(
                f"bundles must be non-empty dicts, got {bundle!r}"
            )
        clean = {}
        for name, amount in bundle.items():
            amount = float(amount)
            if amount <= 0:
                raise ValueError(
                    f"bundle amounts must be > 0, got "
                    f"{name}={amount} in {bundle!r}"
                )
            clean[name] = amount
        out.append(clean)
    return worker.call("request_resources", bundles=out)["count"]
