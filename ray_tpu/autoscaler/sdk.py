"""Programmatic autoscaler API.

Reference: python/ray/autoscaler/sdk/__init__.py request_resources —
applications command a standing capacity target ("make sure the
cluster can hold this much") independent of any queued work; the
autoscaler scales up to satisfy it and holds the satisfying nodes
against idle scale-down until the target is replaced.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(
    num_cpus: Optional[int] = None,
    bundles: Optional[List[Dict[str, float]]] = None,
) -> int:
    """Set (REPLACE) the cluster's standing resource target.

    `num_cpus=N` expands to N one-CPU bundles (the reference's
    semantics — aggregate CPU capacity, placeable anywhere).
    `bundles` is a list of resource dicts that must each fit on some
    node. Calling with neither (or `bundles=[]`) clears the target,
    letting idle nodes scale down again. Returns the number of
    bundles now standing.
    """
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    out: List[Dict[str, float]] = []
    if num_cpus:
        if int(num_cpus) < 0:
            raise ValueError(f"num_cpus must be >= 0, got {num_cpus}")
        out.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    for bundle in bundles or ():
        # Same contract as placement_group(): non-empty
        # {resource: amount > 0} dicts — a zero/negative amount would
        # trivially "fit" every node and pin it against scale-down
        # forever.
        if not isinstance(bundle, dict) or not bundle:
            raise ValueError(
                f"bundles must be non-empty dicts, got {bundle!r}"
            )
        clean = {}
        for name, amount in bundle.items():
            amount = float(amount)
            if amount <= 0:
                raise ValueError(
                    f"bundle amounts must be > 0, got "
                    f"{name}={amount} in {bundle!r}"
                )
            clean[name] = amount
        out.append(clean)
    return worker.call("request_resources", bundles=out)["count"]
