"""Autoscaling (reference: python/ray/autoscaler)."""

from .autoscaler import Monitor, NodeTypeConfig, StandardAutoscaler
from .cluster import AutoscalingCluster, TpuAutoscalingCluster
from .node_provider import FakeMultiNodeProvider, NodeProvider
from .sdk import request_resources

__all__ = [
    "StandardAutoscaler",
    "Monitor",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "AutoscalingCluster",
    "TpuAutoscalingCluster",
    "request_resources",
]
