"""Demand-driven autoscaler.

Reference: python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler.update reconcile loop) + resource_demand_scheduler
.py (bin-pack pending demand into node types) + monitor.py (the
polling daemon); v2 reads the same demand from
GcsAutoscalerStateManager — which is what our `cluster_load` head RPC
mirrors.

Loop: read demand (infeasible tasks + pending placement-group
bundles) -> bin-pack what doesn't fit on live/launching nodes into the
cheapest satisfying node types (bounded by max_workers) -> launch;
terminate workers idle past idle_timeout (respecting min_workers).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .node_provider import NodeProvider


@dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)


def _fits(request: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(
        capacity.get(name, 0.0) >= amount
        for name, amount in request.items()
    )


def _consume(capacity: Dict[str, float], request: Dict[str, float]):
    for name, amount in request.items():
        capacity[name] = capacity.get(name, 0.0) - amount


class StandardAutoscaler:
    def __init__(
        self,
        provider: NodeProvider,
        node_types: Dict[str, NodeTypeConfig],
        *,
        idle_timeout_s: float = 5.0,
        upscaling_speed: float = 1.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.upscaling_speed = upscaling_speed
        self._last_busy: Dict[str, float] = {}
        self._client = None
        self._launched_types: Dict[str, int] = {}

    # -- demand --------------------------------------------------------
    def _load(self) -> dict:
        from .._private.rpc import RpcClient

        if self._client is None:
            self._client = RpcClient(self.provider.head_address)
        return self._client.call("cluster_load")

    # -- one reconcile pass (reference: StandardAutoscaler.update) ----
    def update(self) -> dict:
        load = self._load()
        demand: List[Dict[str, float]] = list(load["infeasible"])
        for pg in load["pending_placement_groups"]:
            demand.extend(pg["bundles"])

        # Capacity view: live worker availability + launching nodes.
        live_available = [
            dict(node["available"])
            for node in load["nodes"]
        ]
        launching: List[Dict[str, float]] = []
        provider_nodes = self.provider.non_terminated_nodes()
        live_ids = {n["node_id"] for n in load["nodes"]}
        for p in provider_nodes:
            if self.provider.cluster_node_id(p) not in live_ids:
                node_type = self.provider.node_type(p)
                if node_type in self.node_types:
                    launching.append(
                        dict(self.node_types[node_type].resources)
                    )

        # min_workers floor.
        to_launch: Dict[str, int] = {}
        counts: Dict[str, int] = {}
        for p in provider_nodes:
            node_type = self.provider.node_type(p)
            counts[node_type] = counts.get(node_type, 0) + 1
        for name, cfg in self.node_types.items():
            if counts.get(name, 0) < cfg.min_workers:
                to_launch[name] = cfg.min_workers - counts.get(name, 0)

        # Bin-pack unmet demand (reference: resource_demand_scheduler).
        pool = live_available + launching
        for request in demand:
            if not request:
                continue
            placed = False
            for capacity in pool:
                if _fits(request, capacity):
                    _consume(capacity, request)
                    placed = True
                    break
            if placed:
                continue
            for name, cfg in sorted(self.node_types.items()):
                total = counts.get(name, 0) + to_launch.get(name, 0)
                if total >= cfg.max_workers:
                    continue
                if _fits(request, cfg.resources):
                    to_launch[name] = to_launch.get(name, 0) + 1
                    fresh = dict(cfg.resources)
                    _consume(fresh, request)
                    pool.append(fresh)
                    placed = True
                    break
            # Unplaceable anywhere: reported, not fatal.

        launched = []
        for name, count in to_launch.items():
            cfg = self.node_types[name]
            for _ in range(count):
                launched.append(
                    self.provider.create_node(
                        name, cfg.resources, cfg.labels
                    )
                )

        # Scale down idle workers (reference: idle node termination).
        terminated = []
        now = time.time()
        cluster_by_id = {n["node_id"]: n for n in load["nodes"]}
        for p in list(provider_nodes):
            cluster_id = self.provider.cluster_node_id(p)
            node = cluster_by_id.get(cluster_id)
            if node is None:
                continue  # still launching
            busy = node["queued"] > 0 or any(
                node["available"].get(k, 0.0) != v
                for k, v in node["total"].items()
            )
            if busy:
                self._last_busy[p] = now
                continue
            idle_for = now - self._last_busy.setdefault(p, now)
            node_type = self.provider.node_type(p)
            cfg = self.node_types.get(node_type)
            type_count = counts.get(node_type, 0)
            if (
                cfg is not None
                and idle_for >= self.idle_timeout_s
                and type_count > cfg.min_workers
            ):
                self.provider.terminate_node(p)
                counts[node_type] = type_count - 1
                terminated.append(p)
        return {
            "demand": len(demand),
            "launched": launched,
            "terminated": terminated,
        }


class Monitor:
    """Background reconcile loop (reference: _private/monitor.py)."""

    def __init__(
        self, autoscaler: StandardAutoscaler, interval_s: float = 0.5
    ):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
