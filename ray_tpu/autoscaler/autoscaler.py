"""Demand-driven autoscaler.

Reference: python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler.update reconcile loop) + resource_demand_scheduler
.py (bin-pack pending demand into node types) + monitor.py (the
polling daemon); v2 reads the same demand from
GcsAutoscalerStateManager — which is what our `cluster_load` head RPC
mirrors.

Loop: read demand (infeasible tasks + pending placement-group
bundles) -> bin-pack what doesn't fit on live/launching nodes into the
cheapest satisfying node types (bounded by max_workers) -> launch;
terminate workers idle past idle_timeout (respecting min_workers).

Slice granularity: a node type with `slice_hosts > 1` is a TPU pod
slice — ONE provider node that boots N host daemons (reference:
gcp/node.py GCPTPUNode spans numNetworkEndpoints hosts). Pending
STRICT_SPREAD gangs (slice_placement_group) are packed onto distinct
hosts, and an unmet gang launches one slice — never N separate nodes —
so slice scale-up is atomic.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .node_provider import NodeProvider

#: Cluster-side label a daemon carries to name its cloud node; N slice
#: host daemons share one value (gcp/node_provider.py writes it into
#: the startup script).
PROVIDER_NODE_LABEL = "rt.io/provider-node"


@dataclass
class NodeTypeConfig:
    resources: Dict[str, float]  # PER-HOST resources
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)
    #: Hosts that join per provider node (1 = plain VM; >1 = pod slice).
    slice_hosts: int = 1


def _fits(request: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(
        capacity.get(name, 0.0) >= amount
        for name, amount in request.items()
    )


def _consume(capacity: Dict[str, float], request: Dict[str, float]):
    for name, amount in request.items():
        capacity[name] = capacity.get(name, 0.0) - amount


class StandardAutoscaler:
    def __init__(
        self,
        provider: NodeProvider,
        node_types: Dict[str, NodeTypeConfig],
        *,
        idle_timeout_s: float = 5.0,
        upscaling_speed: float = 1.0,
        launch_timeout_s: float = 600.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.upscaling_speed = upscaling_speed
        #: How long a provider node's not-yet-joined hosts count as
        #: launching capacity. Past it, a missing host is presumed
        #: dead, not booting — its capacity stops masking demand, so a
        #: gang waiting on it launches replacements instead of
        #: wedging forever (reference: the autoscaler's node launch
        #: timeout / NODE_STARTUP_TIMEOUT).
        self.launch_timeout_s = launch_timeout_s
        self._last_busy: Dict[str, float] = {}
        #: provider node -> first time this reconcile loop saw it
        #: (drives the launch timeout above).
        self._first_seen: Dict[str, float] = {}
        self._client = None
        self._launched_types: Dict[str, int] = {}

    # -- demand --------------------------------------------------------
    def _load(self) -> dict:
        from .._private.rpc import RpcClient

        if self._client is None:
            self._client = RpcClient(self.provider.head_address)
        return self._client.call("cluster_load")

    def _daemons_of(self, provider_id: str, load: dict) -> List[dict]:
        """Cluster nodes belonging to one provider node: by the
        provider-node label (slice nodes, N daemons), falling back to
        the provider's own single-node mapping."""
        daemons = [
            n
            for n in load["nodes"]
            if (n.get("labels") or {}).get(PROVIDER_NODE_LABEL)
            == provider_id
        ]
        if daemons:
            return daemons
        cid = self.provider.cluster_node_id(provider_id)
        return [n for n in load["nodes"] if n["node_id"] == cid]

    # -- one reconcile pass (reference: StandardAutoscaler.update) ----
    def update(self) -> dict:
        load = self._load()

        # Demand. Gangs (STRICT_SPREAD / SPREAD placement groups) need
        # DISTINCT hosts per bundle; everything else packs freely.
        flat: List[Dict[str, float]] = [
            r for r in load["infeasible"] if r
        ]
        gangs: List[List[Dict[str, float]]] = []
        for pg in load["pending_placement_groups"]:
            bundles = [dict(b) for b in pg["bundles"] if b]
            if not bundles:
                continue
            if pg.get("strategy") in ("STRICT_SPREAD", "SPREAD"):
                gangs.append(bundles)
            else:
                flat.extend(bundles)

        # Capacity pools: one entry per live daemon + one per HOST of
        # every launching provider node (a booting v5e-16 slice is 4
        # distinct prospective hosts, not one blob). Two parallel
        # views of the same hosts:
        #   pool     — AVAILABLE capacity; pending task/gang demand
        #              packs here (it will actually consume it);
        #   req_pool — TOTAL capacity; explicit resource_requests pack
        #              here (reference: HandleRequestClusterResource-
        #              Constraint checks node totals regardless of
        #              utilization — a standing target asks "can the
        #              cluster HOLD this", so a busy node still
        #              satisfies its bundle and must not trigger an
        #              over-launch or flap when tasks consume it).
        pool: List[Dict[str, float]] = [
            dict(node["available"]) for node in load["nodes"]
        ]
        req_pool: List[Dict[str, float]] = [
            dict(node["total"]) for node in load["nodes"]
        ]
        now = time.time()
        provider_nodes = self.provider.non_terminated_nodes()
        self._first_seen = {
            p: self._first_seen.get(p, now) for p in provider_nodes
        }
        counts: Dict[str, int] = {}
        for p in provider_nodes:
            node_type = self.provider.node_type(p)
            counts[node_type] = counts.get(node_type, 0) + 1
            cfg = self.node_types.get(node_type)
            if cfg is None:
                continue
            # Launching capacity is counted PER HOST, not per node: a
            # booting v5e-16 slice whose first daemon has joined still
            # owes 3 more hosts, and those prospective hosts must
            # cover the pending gang's remainder — or every reconcile
            # tick during the multi-host boot window launches another
            # whole slice (the test_slice_pg double-launch bug). Only
            # within the launch timeout: past it a missing host is
            # dead, and phantom capacity would wedge the gang forever.
            if now - self._first_seen[p] > self.launch_timeout_s:
                continue
            joined = len(self._daemons_of(p, load))
            missing = max(1, cfg.slice_hosts) - joined
            for _ in range(max(0, missing)):
                pool.append(dict(cfg.resources))
                req_pool.append(dict(cfg.resources))

        # min_workers floor. Floor-booked nodes contribute capacity to
        # the pools so demand packed later (requests, tasks) does not
        # double-launch what the floor already covers.
        to_launch: Dict[str, int] = {}
        for name, cfg in self.node_types.items():
            if counts.get(name, 0) < cfg.min_workers:
                short = cfg.min_workers - counts.get(name, 0)
                to_launch[name] = short
                for _ in range(short * max(1, cfg.slice_hosts)):
                    pool.append(dict(cfg.resources))
                    req_pool.append(dict(cfg.resources))

        def _type_room(name: str) -> int:
            cfg = self.node_types[name]
            return cfg.max_workers - (
                counts.get(name, 0) + to_launch.get(name, 0)
            )

        def _launch_for(request: Dict[str, float], distinct_needed=1):
            """Pick the first node type that fits `request` per host
            and can supply `distinct_needed` hosts in as few provider
            nodes as possible. Returns (available-pool entries,
            total-pool entries) added — one per new host — or None."""
            for name, cfg in sorted(
                self.node_types.items(),
                # Prefer types whose slice covers the whole gang in
                # one node (slice-granular scale-up), then fewer
                # wasted hosts.
                key=lambda kv: (
                    kv[1].slice_hosts < distinct_needed,
                    kv[1].slice_hosts,
                    kv[0],
                ),
            ):
                if _type_room(name) <= 0:
                    continue
                if not _fits(request, cfg.resources):
                    continue
                nodes_needed = max(
                    1, math.ceil(distinct_needed / cfg.slice_hosts)
                )
                if _type_room(name) < nodes_needed:
                    continue
                to_launch[name] = to_launch.get(name, 0) + nodes_needed
                fresh = [
                    dict(cfg.resources)
                    for _ in range(nodes_needed * cfg.slice_hosts)
                ]
                fresh_total = [
                    dict(cfg.resources)
                    for _ in range(nodes_needed * cfg.slice_hosts)
                ]
                pool.extend(fresh)
                req_pool.extend(fresh_total)
                return fresh, fresh_total
            return None

        # Explicit resource requests (reference: autoscaler sdk
        # request_resources): a standing TARGET the cluster must be
        # able to hold. Bundles pack against node TOTALS (req_pool) —
        # matching HandleRequestClusterResourceConstraint — so a node
        # whose availability is temporarily consumed by tasks still
        # satisfies its bundle instead of triggering extra launches
        # and flapping. Satisfied bundles HOLD their nodes against
        # idle scale-down — terminating one would immediately recreate
        # the demand and flap the node back up.
        held_nodes: set = set()
        unsatisfied_requests = 0
        daemon_count = len(load["nodes"])
        requests = load.get("resource_requests") or []
        for request in requests:
            placed = False
            for idx, capacity in enumerate(req_pool):
                if _fits(request, capacity):
                    _consume(capacity, request)
                    if idx < daemon_count:
                        held_nodes.add(load["nodes"][idx]["node_id"])
                    placed = True
                    break
            if not placed:
                added = _launch_for(request)
                if added:
                    _consume(added[1][0], request)
                else:
                    # No node type fits (or max_workers reached): the
                    # standing target cannot be met — surface it
                    # rather than silently dropping it every tick.
                    unsatisfied_requests += 1

        # Bin-pack flat demand (reference: resource_demand_scheduler).
        for request in flat:
            placed = False
            for capacity in pool:
                if _fits(request, capacity):
                    _consume(capacity, request)
                    placed = True
                    break
            if not placed:
                added = _launch_for(request)
                if added:
                    _consume(added[0][0], request)
            # Unplaceable anywhere: reported, not fatal.

        # Pack gangs: each bundle on a DISTINCT pool entry; an unmet
        # remainder launches whole slices (one provider node covers up
        # to slice_hosts bundles — the slice_placement_group ->
        # tpu-v5e-16 path).
        for bundles in gangs:
            used: set = set()
            unplaced: List[Dict[str, float]] = []
            for request in bundles:
                placed = False
                for idx, capacity in enumerate(pool):
                    if idx in used:
                        continue
                    if _fits(request, capacity):
                        _consume(capacity, request)
                        used.add(idx)
                        placed = True
                        break
                if not placed:
                    unplaced.append(request)
            if unplaced:
                # Launch hosts each able to hold ANY of the unplaced
                # bundles: size the per-host requirement as the
                # elementwise max across bundles (slice gangs are
                # uniform chip sets, but a heterogeneous STRICT_SPREAD
                # must not pick a host shape that fits only one
                # bundle kind).
                need: Dict[str, float] = {}
                for request in unplaced:
                    for name, amount in request.items():
                        need[name] = max(need.get(name, 0.0), amount)
                added = _launch_for(need, len(unplaced))
                if added:
                    for request, capacity in zip(unplaced, added[0]):
                        _consume(capacity, request)

        launched = []
        for name, count in to_launch.items():
            cfg = self.node_types[name]
            for _ in range(count):
                launched.append(
                    self.provider.create_node(
                        name, cfg.resources, cfg.labels
                    )
                )

        # Scale down idle provider nodes. A slice node is idle only
        # when EVERY host daemon is idle (reference: idle node
        # termination; v2 kills whole TPU pods, never partial slices).
        terminated = []
        for p in list(provider_nodes):
            daemons = self._daemons_of(p, load)
            if not daemons:
                continue  # still launching
            if any(n["node_id"] in held_nodes for n in daemons):
                # Capacity pinned by an explicit resource request.
                self._last_busy[p] = now
                continue
            busy = any(
                node["queued"] > 0
                or any(
                    node["available"].get(k, 0.0) != v
                    for k, v in node["total"].items()
                )
                for node in daemons
            )
            if busy:
                self._last_busy[p] = now
                continue
            idle_for = now - self._last_busy.setdefault(p, now)
            node_type = self.provider.node_type(p)
            cfg = self.node_types.get(node_type)
            type_count = counts.get(node_type, 0)
            if (
                cfg is not None
                and idle_for >= self.idle_timeout_s
                and type_count > cfg.min_workers
            ):
                self.provider.terminate_node(p)
                counts[node_type] = type_count - 1
                terminated.append(p)
        return {
            "demand": len(flat)
            + sum(len(g) for g in gangs)
            + len(requests),
            "unsatisfied_requests": unsatisfied_requests,
            "launched": launched,
            "terminated": terminated,
        }


class Monitor:
    """Background reconcile loop (reference: _private/monitor.py)."""

    def __init__(
        self, autoscaler: StandardAutoscaler, interval_s: float = 0.5
    ):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
