"""@remote functions (reference: python/ray/remote_function.py —
RemoteFunction._remote:303 submits through the core worker; .options()
re-binds per-call overrides)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional


class RemoteFunction:
    def __init__(self, func, options: Optional[Dict[str, Any]] = None):
        from ._private.options import validate_options

        self._function = func
        self._options = dict(options or {})
        # Every construction path (decorator, .options() clone) funnels
        # here: a typo'd key raises with the valid key set instead of
        # being silently merged and ignored at submission.
        validate_options("task", self._options)
        self._exported_key: Optional[str] = None
        #: (generation, func_key, name, num_returns, resources,
        #: max_retries) — resolved-once submit plan for static options
        #: (api_internal.submit_function hot path). Never copied by
        #: .options(): a clone's options differ by construction.
        self._submit_plan = None
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly; use "
            f"{self._function.__name__}.remote()."
        )

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        clone = RemoteFunction(self._function, merged)
        clone._exported_key = self._exported_key
        return clone

    def remote(self, *args, **kwargs):
        from ._private.api_internal import submit_function

        return submit_function(self, args, kwargs)

    def bind(self, *args, **kwargs):
        """Lazy DAG node for this task invocation (reference:
        python/ray/dag/function_node.py)."""
        from .dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    # internal
    @property
    def underlying(self):
        return self._function

    @property
    def task_options(self) -> Dict[str, Any]:
        return self._options
