"""Utility APIs (reference: python/ray/util/)."""

from .placement_group import (
    PlacementGroup,
    get_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "get_placement_group",
]
