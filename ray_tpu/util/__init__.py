"""Utility APIs (reference: python/ray/util/)."""

from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
)

__all__ = [
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
]
