"""Utility APIs (reference: python/ray/util/)."""

from .placement_group import (
    PlacementGroup,
    get_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)



def list_named_actors(all_namespaces: bool = False):
    """Names of all live named actors (reference:
    ray.util.list_named_actors). With all_namespaces=True, returns
    [{"name": ..., "namespace": ...}] across every namespace;
    otherwise a flat name list scoped to the session's namespace
    (rt.init(namespace=...), "default" otherwise)."""
    from . import state

    rows = [
        row
        for row in state.list_actors()
        if row.get("name") and row.get("state") != "DEAD"
    ]
    if all_namespaces:
        return [
            {"name": row["name"], "namespace": row.get("namespace")}
            for row in rows
        ]
    mine = state._worker().namespace
    return [
        row["name"]
        for row in rows
        if row.get("namespace", "default") == mine  # rt: noqa[RT006] — wire-compat: rows from old daemons lack the field
    ]


__all__ = [
    "list_named_actors",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "get_placement_group",
]
