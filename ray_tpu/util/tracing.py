"""Chrome-trace timeline export.

Reference: `ray.timeline()` builds a chrome://tracing JSON from the
per-task state-transition events batched into GcsTaskManager
(core_worker/task_event_buffer.h). Our head records the same
transitions (daemon _record_task_event); this module folds them into
duration events: one slice per task from its first RUNNING-adjacent
state to its final state.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import List, Optional

_BEGIN_STATES = {
    "PENDING_ARGS_AVAIL",
    "FORWARDED",
    "PENDING_NODE_ASSIGNMENT",
}
_END_STATES = {"FINISHED", "FAILED", "DONE"}


def timeline_to_chrome_trace(
    events: List[dict], path: Optional[str] = None
) -> List[dict]:
    """Fold task state events into chrome trace 'X' slices; returns the
    trace (and writes JSON to `path` when given)."""
    by_task = defaultdict(list)
    for event in events:
        by_task[event["task_id"]].append(event)
    trace = []
    for task_id, task_events in by_task.items():
        task_events.sort(key=lambda e: e["time"])
        start = task_events[0]
        end = task_events[-1]
        duration_us = max(1.0, (end["time"] - start["time"]) * 1e6)
        trace.append(
            {
                "name": start.get("name") or start.get("kind", "task"),
                "cat": start.get("kind", "task"),
                "ph": "X",
                "ts": start["time"] * 1e6,
                "dur": duration_us,
                "pid": "cluster",
                "tid": task_id[:8],
                "args": {
                    "task_id": task_id,
                    "final_state": end["state"],
                    "states": [e["state"] for e in task_events],
                },
            }
        )
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def export_timeline(path: str) -> List[dict]:
    """`ray.timeline(filename=...)` equivalent: fetch events from the
    head and write a chrome trace."""
    import ray_tpu

    return timeline_to_chrome_trace(ray_tpu.timeline(), path)
