"""Chrome-trace timeline export.

Reference: `ray.timeline()` builds a chrome://tracing JSON from the
per-task state-transition events batched into GcsTaskManager
(core_worker/task_event_buffer.h). Our head records the same
transitions (daemon _record_task_event); this module folds them into
duration events: one slice per task from its first RUNNING-adjacent
state to its final state.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import List, Optional

_BEGIN_STATES = {
    "PENDING_ARGS_AVAIL",
    "FORWARDED",
    "PENDING_NODE_ASSIGNMENT",
    # Re-queue transitions: a retried/reconstructing task is waiting
    # to be scheduled again — that wait is queue time, not runtime.
    "RETRY",
    "RECONSTRUCTING",
}
#: Transitions that put an already-dispatched task BACK in the queue:
#: the lifecycle splits into attempts here, each with its own slice
#: and queue accounting (one slice across a retry would bill the
#: reschedule wait as runtime).
_REQUEUE_STATES = {"RETRY", "RECONSTRUCTING"}
_END_STATES = {"FINISHED", "FAILED", "DONE"}


def timeline_to_chrome_trace(
    events: List[dict], path: Optional[str] = None
) -> List[dict]:
    """Fold task state events into chrome trace 'X' slices; returns the
    trace (and writes JSON to `path` when given)."""
    by_task = defaultdict(list)
    for event in events:
        by_task[event["task_id"]].append(event)
    trace = []
    for task_id, task_events in by_task.items():
        task_events.sort(key=lambda e: e["time"])
        # Split the lifecycle into attempts at re-queue transitions,
        # then anchor each attempt's slice at its first
        # RUNNING-adjacent event: a single slice from submission to
        # completion would bill queue time (PENDING_*/FORWARDED, and
        # any RETRY reschedule wait) as runtime. Queue time is still
        # reported — as each slice's own arg, not inside it.
        attempts: List[List[dict]] = [[]]
        for e in task_events:
            if e["state"] in _REQUEUE_STATES and attempts[-1]:
                # The requeue event both CLOSES the running attempt
                # (its end timestamp) and OPENS the next one's queue
                # period.
                attempts[-1].append(e)
                attempts.append([])
            attempts[-1].append(e)
        for idx, attempt in enumerate(attempts):
            submitted = attempt[0]
            start = next(
                (
                    e
                    for e in attempt
                    if e["state"] not in _BEGIN_STATES
                ),
                None,
            )
            end = next(
                (e for e in attempt if e["state"] in _END_STATES),
                attempt[-1],
            )
            if start is None:
                # This attempt never left the queue: its whole span
                # is queue time, not runtime — render a minimal
                # marker slice at its start so nothing reads as
                # execution.
                start = submitted
                queued_us = max(
                    0.0, (end["time"] - submitted["time"]) * 1e6
                )
                duration_us = 1.0
            else:
                queued_us = max(
                    0.0, (start["time"] - submitted["time"]) * 1e6
                )
                duration_us = max(
                    1.0, (end["time"] - start["time"]) * 1e6
                )
            args = {
                "task_id": task_id,
                "final_state": end["state"],
                "queued_us": round(queued_us, 1),
                "states": [e["state"] for e in attempt],
            }
            if len(attempts) > 1:
                args["attempt"] = idx + 1
                args["attempts"] = len(attempts)
            trace.append(
                {
                    "name": task_events[0].get("name")
                    or task_events[0].get("kind", "task"),
                    "cat": task_events[0].get("kind", "task"),
                    "ph": "X",
                    "ts": start["time"] * 1e6,
                    "dur": duration_us,
                    "pid": "cluster",
                    "tid": task_id[:8],
                    "args": args,
                }
            )
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def export_timeline(path: str) -> List[dict]:
    """`ray.timeline(filename=...)` equivalent: fetch events from the
    head and write a chrome trace."""
    import ray_tpu

    return timeline_to_chrome_trace(ray_tpu.timeline(), path)


# ---------------------------------------------------------------------
# Distributed spans with OTLP-JSON export (reference: ray's OTel
# integration, python/ray/util/tracing/ — spans around task submit and
# execution with remote context propagation). Self-contained: the OTLP
# wire shape is produced directly, no opentelemetry SDK needed, so any
# OTLP/JSON-ingesting backend (collector file receiver, Tempo, Jaeger)
# reads the export.
# ---------------------------------------------------------------------

import contextvars
import os as _os
import time as _time
from contextlib import contextmanager

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "rt_current_span", default=None
)


class SpanContext:
    __slots__ = ("trace_id", "span_id", "attributes")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id
        #: Mutable: add_span_attributes() writes here until span exit.
        self.attributes: dict = {}


def add_span_attributes(**attributes) -> None:
    """Attach attributes to the CURRENT span (exported at its exit).
    No-op outside any span — callers never need to guard."""
    ctx = _current_span.get()
    if ctx is not None and hasattr(ctx, "attributes"):
        ctx.attributes.update(
            {str(k): str(v) for k, v in attributes.items()}
        )


def current_span_context() -> "SpanContext | None":
    return _current_span.get()


def _rand_hex(nbytes: int) -> str:
    return _os.urandom(nbytes).hex()


def _record_span(record: dict) -> None:
    """Ship one finished span to the head's DEDICATED span ring (not
    the task-event ring: sharing one deque would let busy task streams
    evict spans — and vice versa — and force every event consumer to
    filter foreign records)."""
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        return
    try:
        worker._client.notify("span_event", spans=[record])
    except Exception:
        pass


@contextmanager
def span(name: str, **attributes):
    """Open a span; nests under the current one (including a parent
    propagated from a remote caller). Usable in drivers and tasks."""
    parent = _current_span.get()
    ctx = SpanContext(
        parent.trace_id if parent else _rand_hex(16), _rand_hex(8)
    )
    start = _time.time_ns()
    token = _current_span.set(ctx)
    error = None
    try:
        yield ctx
    except BaseException as e:  # noqa: BLE001 — recorded then re-raised
        error = repr(e)
        raise
    finally:
        _current_span.reset(token)
        _record_span({
            "name": name,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_span_id": parent.span_id if parent else "",
            "start_ns": start,
            "end_ns": _time.time_ns(),
            "attributes": {
                **{str(k): str(v) for k, v in attributes.items()},
                **ctx.attributes,
                **({"error": error} if error else {}),
            },
        })


def inject_context() -> "dict | None":
    """Wire-shippable form of the CURRENT span context — exactly the
    dict `remote_parent()` adopts on the receiving side. None outside
    any span, so callers can ship it unconditionally (serve's router
    attaches it to every request context)."""
    ctx = _current_span.get()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


@contextmanager
def remote_parent(trace_ctx: "dict | None"):
    """Adopt a caller-propagated span context (worker-side, around
    task execution)."""
    if not trace_ctx:
        yield
        return
    token = _current_span.set(
        SpanContext(trace_ctx["trace_id"], trace_ctx["span_id"])
    )
    try:
        yield
    finally:
        _current_span.reset(token)


def _otlp_value(v: str) -> dict:
    return {"stringValue": v}


def spans_to_otlp(records) -> dict:
    """Span records -> one OTLP/JSON ExportTraceServiceRequest."""
    return {
        "resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": _otlp_value("ray_tpu"),
            }]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.util.tracing"},
                "spans": [{
                    "traceId": r["trace_id"],
                    "spanId": r["span_id"],
                    **({"parentSpanId": r["parent_span_id"]}
                       if r.get("parent_span_id") else {}),
                    "name": r["name"],
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": str(r["start_ns"]),
                    "endTimeUnixNano": str(r["end_ns"]),
                    "attributes": [
                        {"key": k, "value": _otlp_value(v)}
                        for k, v in (r.get("attributes") or {}).items()
                    ],
                } for r in records],
            }],
        }]
    }


def spans_to_chrome_trace(records) -> List[dict]:
    """Span records -> chrome trace 'X' slices (one pid per trace,
    one tid per span chain depth proxy: the span id). Lets spans sit
    in the same chrome://tracing view as task slices and step
    phases (`ray_tpu doctor --trace`)."""
    trace = []
    for r in records:
        trace.append(
            {
                "name": r["name"],
                "cat": "span",
                "ph": "X",
                "ts": r["start_ns"] / 1e3,
                "dur": max(
                    1.0, (r["end_ns"] - r["start_ns"]) / 1e3
                ),
                "pid": f"trace:{r['trace_id'][:8]}",
                "tid": r.get("parent_span_id") or "root",
                "args": dict(r.get("attributes") or {}),
            }
        )
    return trace


def merge_chrome_trace(
    task_events: List[dict],
    span_records: List[dict],
    step_records: List[dict],
    path: Optional[str] = None,
) -> List[dict]:
    """One chrome trace out of the three observability streams: task
    state-event slices (queue time excluded per the slice anchor
    above), finished spans, and per-step per-rank phase slices. The
    `ray_tpu doctor --trace out.json` artifact."""
    from .._private.step_telemetry import steps_to_chrome_trace

    trace = timeline_to_chrome_trace(task_events)
    trace.extend(spans_to_chrome_trace(span_records))
    trace.extend(steps_to_chrome_trace(step_records))
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def export_otlp(path: "str | None" = None) -> dict:
    """Fetch recorded spans from the head and write/return OTLP JSON
    (`ray.timeline()`'s role for the span world)."""
    from .. import exceptions as exc
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError(
            "export_otlp() requires an initialized session "
            "(call ray_tpu.init() first)"
        )
    records = worker.call("list_spans", limit=10000)["spans"]
    otlp = spans_to_otlp(records)
    if path:
        with open(path, "w") as f:
            json.dump(otlp, f)
    return otlp
