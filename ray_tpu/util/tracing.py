"""Chrome-trace timeline export.

Reference: `ray.timeline()` builds a chrome://tracing JSON from the
per-task state-transition events batched into GcsTaskManager
(core_worker/task_event_buffer.h). Our head records the same
transitions (daemon _record_task_event); this module folds them into
duration events: one slice per task from its first RUNNING-adjacent
state to its final state.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import List, Optional

_BEGIN_STATES = {
    "PENDING_ARGS_AVAIL",
    "FORWARDED",
    "PENDING_NODE_ASSIGNMENT",
}
_END_STATES = {"FINISHED", "FAILED", "DONE"}


def timeline_to_chrome_trace(
    events: List[dict], path: Optional[str] = None
) -> List[dict]:
    """Fold task state events into chrome trace 'X' slices; returns the
    trace (and writes JSON to `path` when given)."""
    by_task = defaultdict(list)
    for event in events:
        by_task[event["task_id"]].append(event)
    trace = []
    for task_id, task_events in by_task.items():
        task_events.sort(key=lambda e: e["time"])
        start = task_events[0]
        end = task_events[-1]
        duration_us = max(1.0, (end["time"] - start["time"]) * 1e6)
        trace.append(
            {
                "name": start.get("name") or start.get("kind", "task"),
                "cat": start.get("kind", "task"),
                "ph": "X",
                "ts": start["time"] * 1e6,
                "dur": duration_us,
                "pid": "cluster",
                "tid": task_id[:8],
                "args": {
                    "task_id": task_id,
                    "final_state": end["state"],
                    "states": [e["state"] for e in task_events],
                },
            }
        )
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def export_timeline(path: str) -> List[dict]:
    """`ray.timeline(filename=...)` equivalent: fetch events from the
    head and write a chrome trace."""
    import ray_tpu

    return timeline_to_chrome_trace(ray_tpu.timeline(), path)


# ---------------------------------------------------------------------
# Distributed spans with OTLP-JSON export (reference: ray's OTel
# integration, python/ray/util/tracing/ — spans around task submit and
# execution with remote context propagation). Self-contained: the OTLP
# wire shape is produced directly, no opentelemetry SDK needed, so any
# OTLP/JSON-ingesting backend (collector file receiver, Tempo, Jaeger)
# reads the export.
# ---------------------------------------------------------------------

import contextvars
import os as _os
import time as _time
from contextlib import contextmanager

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "rt_current_span", default=None
)


class SpanContext:
    __slots__ = ("trace_id", "span_id", "attributes")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id
        #: Mutable: add_span_attributes() writes here until span exit.
        self.attributes: dict = {}


def add_span_attributes(**attributes) -> None:
    """Attach attributes to the CURRENT span (exported at its exit).
    No-op outside any span — callers never need to guard."""
    ctx = _current_span.get()
    if ctx is not None and hasattr(ctx, "attributes"):
        ctx.attributes.update(
            {str(k): str(v) for k, v in attributes.items()}
        )


def current_span_context() -> "SpanContext | None":
    return _current_span.get()


def _rand_hex(nbytes: int) -> str:
    return _os.urandom(nbytes).hex()


def _record_span(record: dict) -> None:
    """Ship one finished span to the head's DEDICATED span ring (not
    the task-event ring: sharing one deque would let busy task streams
    evict spans — and vice versa — and force every event consumer to
    filter foreign records)."""
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        return
    try:
        worker._client.notify("span_event", spans=[record])
    except Exception:
        pass


@contextmanager
def span(name: str, **attributes):
    """Open a span; nests under the current one (including a parent
    propagated from a remote caller). Usable in drivers and tasks."""
    parent = _current_span.get()
    ctx = SpanContext(
        parent.trace_id if parent else _rand_hex(16), _rand_hex(8)
    )
    start = _time.time_ns()
    token = _current_span.set(ctx)
    error = None
    try:
        yield ctx
    except BaseException as e:  # noqa: BLE001 — recorded then re-raised
        error = repr(e)
        raise
    finally:
        _current_span.reset(token)
        _record_span({
            "name": name,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_span_id": parent.span_id if parent else "",
            "start_ns": start,
            "end_ns": _time.time_ns(),
            "attributes": {
                **{str(k): str(v) for k, v in attributes.items()},
                **ctx.attributes,
                **({"error": error} if error else {}),
            },
        })


@contextmanager
def remote_parent(trace_ctx: "dict | None"):
    """Adopt a caller-propagated span context (worker-side, around
    task execution)."""
    if not trace_ctx:
        yield
        return
    token = _current_span.set(
        SpanContext(trace_ctx["trace_id"], trace_ctx["span_id"])
    )
    try:
        yield
    finally:
        _current_span.reset(token)


def _otlp_value(v: str) -> dict:
    return {"stringValue": v}


def spans_to_otlp(records) -> dict:
    """Span records -> one OTLP/JSON ExportTraceServiceRequest."""
    return {
        "resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": _otlp_value("ray_tpu"),
            }]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.util.tracing"},
                "spans": [{
                    "traceId": r["trace_id"],
                    "spanId": r["span_id"],
                    **({"parentSpanId": r["parent_span_id"]}
                       if r.get("parent_span_id") else {}),
                    "name": r["name"],
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": str(r["start_ns"]),
                    "endTimeUnixNano": str(r["end_ns"]),
                    "attributes": [
                        {"key": k, "value": _otlp_value(v)}
                        for k, v in (r.get("attributes") or {}).items()
                    ],
                } for r in records],
            }],
        }]
    }


def export_otlp(path: "str | None" = None) -> dict:
    """Fetch recorded spans from the head and write/return OTLP JSON
    (`ray.timeline()`'s role for the span world)."""
    from .. import exceptions as exc
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError(
            "export_otlp() requires an initialized session "
            "(call ray_tpu.init() first)"
        )
    records = worker.call("list_spans", limit=10000)["spans"]
    otlp = spans_to_otlp(records)
    if path:
        with open(path, "w") as f:
            json.dump(otlp, f)
    return otlp
