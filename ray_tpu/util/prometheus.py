"""Prometheus text-format 0.0.4 exposition.

Reference: src/ray/stats/metric.h + the reference's per-node metrics
agents exporting OpenCensus views to Prometheus. Here the head's
aggregated metric table (daemon `_h_metrics_summary`) is rendered
directly: counters and gauges become labeled series, histograms become
cumulative ``le`` bucket series with the mandatory ``+Inf`` bucket,
``_sum`` and ``_count``.

Renders FROM the wire shape `metrics_summary()` returns, so the same
function serves the dashboard's ``/metrics`` endpoint and the
``ray_tpu metrics scrape`` CLI.

Series-emission rule (keeps PromQL ``sum()`` double-count-free):
``by_node`` present -> only per-node labeled series; else ``by_tags``
present -> one series per tag set (the empty tag set renders
unlabeled); else the single aggregate value.

Naming convention (enforced by lint rule RT009 for metrics declared in
the package): ``^[a-z][a-z0-9_]*$``, counters end in ``_total``,
label keys ``^[a-z][a-z0-9_]*$``. Dots/dashes in legacy user metric
names are sanitized to underscores at exposition time.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["render_prometheus", "METRIC_NAME_RE", "LABEL_KEY_RE"]

#: The documented naming convention (see README "Metrics export"):
#: lowercase snake_case names; counters additionally end in `_total`.
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
LABEL_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*$")

_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize_name(name: str) -> str:
    safe = _INVALID_CHARS.sub("_", str(name))
    if not safe or not (safe[0].isalpha() or safe[0] in "_:"):
        safe = "_" + safe
    return safe


def _escape_help(text: str) -> str:
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _labels(pairs: Iterable[Tuple[str, str]]) -> str:
    rendered = ",".join(
        f'{_sanitize_name(k)}="{_escape_label_value(v)}"'
        for k, v in pairs
    )
    return "{" + rendered + "}" if rendered else ""


def _parse_tag_key(flat: str) -> List[Tuple[str, str]]:
    """Inverse of the head's ``"|".join(f"{k}={v}")`` tag flattening.
    Values may themselves contain ``=`` (only the first one splits);
    a ``|`` inside a value is not recoverable — documented limitation
    of the flat form."""
    if not flat:
        return []
    pairs = []
    for part in flat.split("|"):
        key, _, value = part.partition("=")
        pairs.append((key, value))
    return pairs


def _fmt(value) -> str:
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    return repr(float(value))


def _bucket_pairs(buckets: Dict[str, float]) -> List[Tuple[str, float]]:
    """``{"le_0.005": 3, ..., "inf": 9}`` -> ordered cumulative
    ``(le-label, count)`` pairs ending at ``+Inf``. The head already
    accumulates cumulatively in boundary order; re-sort defensively by
    the numeric bound and enforce monotonicity so a malformed entry
    can never emit a decreasing series (which Prometheus rejects)."""
    numbered = []
    inf_count = None
    for key, count in buckets.items():
        if key == "inf":
            inf_count = float(count)
            continue
        if key.startswith("le_"):
            try:
                bound = float(key[3:])
            except ValueError:
                continue
            numbered.append((bound, float(count)))
    numbered.sort(key=lambda pair: pair[0])
    out: List[Tuple[str, float]] = []
    running = 0.0
    for bound, count in numbered:
        running = max(running, count)
        out.append((f"{bound:g}", running))
    if inf_count is not None:
        running = max(running, inf_count)
    out.append(("+Inf", running))
    return out


def _histogram_lines(
    safe: str, series: dict, base_labels: List[Tuple[str, str]]
) -> List[str]:
    count = float(series.get("count", 0) or 0)
    total = float(series.get("sum", 0.0) or 0.0)
    buckets = series.get("buckets") or {}
    pairs = _bucket_pairs(buckets) if buckets else [("+Inf", count)]
    # The +Inf bucket must equal _count; a reservoir-less entry (no
    # declared boundaries) still gets its mandatory +Inf series.
    if pairs and pairs[-1][0] == "+Inf":
        pairs[-1] = ("+Inf", max(pairs[-1][1], count))
    lines = []
    for le, cumulative in pairs:
        lines.append(
            f"{safe}_bucket"
            f"{_labels(base_labels + [('le', le)])} "
            f"{_fmt(cumulative)}"
        )
    lines.append(f"{safe}_sum{_labels(base_labels)} {_fmt(total)}")
    lines.append(
        f"{safe}_count{_labels(base_labels)} {_fmt(count)}"
    )
    return lines


def render_prometheus(metrics: Dict[str, dict]) -> str:
    """Render a `metrics_summary()` mapping as Prometheus text-format
    0.0.4 (the dashboard's ``/metrics`` payload)."""
    lines: List[str] = []
    for name in sorted(metrics):
        entry = metrics[name]
        kind = entry.get("kind")
        safe = _sanitize_name(name)
        if entry.get("description"):
            lines.append(
                f"# HELP {safe} {_escape_help(entry['description'])}"
            )
        if kind == "counter":
            lines.append(f"# TYPE {safe} counter")
            value_key = "total"
        elif kind == "gauge":
            lines.append(f"# TYPE {safe} gauge")
            value_key = "value"
        elif kind == "histogram":
            lines.append(f"# TYPE {safe} histogram")
            value_key = None
        else:
            lines.append(f"# TYPE {safe} untyped")
            value_key = "value"

        by_node = entry.get("by_node")
        by_tags = entry.get("by_tags")
        if by_node:
            # Core runtime metrics: ONLY per-node labeled series (the
            # reference exports per-node series through each node's
            # metrics agent). No unlabeled cluster line — it would
            # double-count under PromQL sum().
            for node, value in sorted(by_node.items()):
                lines.append(
                    f"{safe}{_labels([('node', node)])} {_fmt(value)}"
                )
            continue
        if kind == "histogram":
            series_list: List[Tuple[List[Tuple[str, str]], dict]]
            if by_tags:
                series_list = [
                    (_parse_tag_key(flat), series)
                    for flat, series in sorted(by_tags.items())
                ]
            else:
                series_list = [([], entry)]
            for base_labels, series in series_list:
                lines.extend(
                    _histogram_lines(safe, series, base_labels)
                )
            continue
        if by_tags:
            for flat, series in sorted(by_tags.items()):
                lines.append(
                    f"{safe}{_labels(_parse_tag_key(flat))} "
                    f"{_fmt(series.get(value_key, 0.0) or 0.0)}"
                )
        else:
            lines.append(
                f"{safe} {_fmt(entry.get(value_key, 0.0) or 0.0)}"
            )
    return "\n".join(lines) + "\n"
