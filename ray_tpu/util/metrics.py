"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py — application metrics recorded
from any worker, aggregated cluster-wide (the reference flows through
per-node metrics agents into Prometheus; here records flow through the
node daemon's KV-style metric table on the head and are queried with
`metrics_summary()`; a Prometheus text endpoint rides the dashboard).
"""

from __future__ import annotations

import logging
import os
import threading
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from .. import exceptions as exc
from ..devtools.lock_witness import make_lock

logger = logging.getLogger(__name__)

_FLUSH_INTERVAL_S = 0.5
#: Records kept while the head is unreachable (failed flushes requeue
#: their batch rather than dropping it; oldest age out past this cap).
_MAX_BUFFERED = 10000


def _worker():
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


class _Buffer:
    """Per-process record buffer with a background flusher.

    Lifecycle: `reset()` (called by ray_tpu.shutdown()) stops the
    flusher thread and drops the singleton, so a re-init gets a fresh
    buffer + thread bound to the NEW worker — the old flusher no
    longer survives shutdown silently dropping records against a dead
    session. A flush SEALS the pending records into a numbered batch
    and delivers sealed batches in order, each tagged (sender, seq);
    the head drops seqs it already applied, so a retry after a lost
    reply cannot double-count — outages cost retries, not records and
    not duplicates. Failed batches stay sealed (bounded) for the next
    tick; the background loop warns ONCE per outage instead of
    swallowing every exception forever, while an explicit `flush()`
    raises."""

    _instance: Optional["_Buffer"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.records: List[tuple] = []
        self.records_lock = make_lock("metrics.records")
        self._stop = threading.Event()
        self._warned = False
        self._sender = uuid.uuid4().hex
        self._seq = 0
        self._sealed: List[Tuple[int, List[tuple]]] = []
        # Pre-flush drains: callables that push their own aggregated
        # records right before each seal (the worker's get-provenance
        # aggregates ride these — batched per flush tick, never one
        # record per get). Registered per buffer generation: fork and
        # shutdown drop the singleton, so hooks never outlive the
        # session they aggregate for.
        self._drain_hooks: List = []
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    @classmethod
    def get(cls) -> "_Buffer":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Final best-effort flush, stop the flusher, drop the
        singleton (ray_tpu.shutdown() path)."""
        with cls._lock:
            buf, cls._instance = cls._instance, None
        if buf is None:
            return
        buf._stop.set()
        buf.flush(raise_on_error=False)
        buf.thread.join(timeout=2.0)

    @classmethod
    def _reset_after_fork(cls) -> None:
        # The flusher thread does not survive fork; drop any
        # inherited singleton so the child lazily creates a live one
        # (no lock: the parent may have held it mid-fork).
        cls._instance = None

    def push(self, record: tuple) -> None:
        with self.records_lock:
            self.records.append(record)

    def add_drain_hook(self, hook) -> None:
        """Register a callable run before each flush seals a batch
        (idempotent per hook object). Hooks push records via push();
        a raising hook is dropped from the list, never the flush."""
        with self.records_lock:
            if hook not in self._drain_hooks:
                self._drain_hooks.append(hook)

    def _loop(self) -> None:
        while not self._stop.wait(_FLUSH_INTERVAL_S):
            self.flush(raise_on_error=False)

    def _seal_and_trim_locked(self) -> None:
        """Move pending records into a new sealed batch and enforce
        the buffered-record cap across sealed batches. Caller holds
        `records_lock`. Boundary-carrying records (the 5-tuple each
        Histogram sends ONCE per buffer generation) survive trimming
        unconditionally: age them out and the head could never bucket
        that histogram again this process lifetime."""
        if self.records:
            self._seq += 1
            self._sealed.append((self._seq, self.records))
            self.records = []
        overflow = (
            sum(len(b) for _, b in self._sealed) - _MAX_BUFFERED
        )
        if overflow > 0:
            trimmed = []
            for seq, batch in self._sealed:
                if overflow > 0:
                    cut = min(overflow, len(batch))
                    declares = [
                        r for r in batch[:cut] if len(r) > 4
                    ]
                    batch = declares + batch[cut:]
                    overflow -= cut
                if batch:
                    trimmed.append((seq, batch))
            self._sealed = trimmed

    def flush(self, raise_on_error: bool = True) -> None:
        with self.records_lock:
            hooks = list(self._drain_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception:
                # A broken drain must not wedge every future flush.
                with self.records_lock:
                    if hook in self._drain_hooks:
                        self._drain_hooks.remove(hook)
        with self.records_lock:
            self._seal_and_trim_locked()
            pending = list(self._sealed)
        for seq, batch in pending:
            try:
                # Bounded: an accepted-but-never-answered head (the
                # wedged-cluster case the doctor exists to diagnose)
                # must fail this flush — not hang rt.diagnose()'s
                # pre-read flush or shutdown()'s final one forever. A
                # timed-out batch stays sealed; head-side seq dedup
                # absorbs the retry if it was actually applied.
                _worker().call(
                    "metrics_record",
                    records=batch,
                    sender=self._sender,
                    seq=seq,
                    timeout=30.0,
                )
            except Exception as e:
                # The batch stays sealed under its seq for the next
                # tick: retried delivery is deduplicated head-side,
                # so an outage costs retries, not records and not
                # double-counts.
                if raise_on_error:
                    raise exc.RayTpuError(
                        f"metrics flush failed: {e}"
                    ) from e
                if not self._warned:
                    self._warned = True
                    logger.warning(
                        "metrics flush failed (%s); records are "
                        "buffered (max %d) and the flusher will keep "
                        "retrying — this is logged once per outage",
                        e,
                        _MAX_BUFFERED,
                    )
                return
            with self.records_lock:
                self._sealed = [
                    (s, b) for s, b in self._sealed if s != seq
                ]
        self._warned = False


os.register_at_fork(after_in_child=_Buffer._reset_after_fork)


class _Metric:
    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Sequence[str] = (),
    ):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value < 0:
            raise ValueError("Counter.inc() takes a non-negative value")
        _Buffer.get().push(
            (self.KIND, self._name, float(value), self._tags(tags))
        )


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[dict] = None):
        _Buffer.get().push(
            (self.KIND, self._name, float(value), self._tags(tags))
        )


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = (),
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        # Sorted up front: the head buckets with bisect against them.
        self._boundaries = sorted(float(b) for b in boundaries)
        self._declared_for: Optional["_Buffer"] = None

    def observe(self, value: float, tags: Optional[dict] = None):
        # Boundaries ride the instance's FIRST record per buffer
        # generation (5th field; counters and gauges stay 4-tuples):
        # the head keeps first-seen boundaries per name, so repeating
        # them on every observation is pure wire/CPU overhead. Keyed
        # to the buffer object — shutdown/re-init and fork build a
        # fresh buffer, whose (possibly new) head needs a re-declare.
        buf = _Buffer.get()
        rec = (self.KIND, self._name, float(value), self._tags(tags))
        if self._declared_for is not buf:
            rec = rec + (tuple(self._boundaries),)
            self._declared_for = buf
        buf.push(rec)


def flush() -> None:
    """Force-flush this process's buffered records (tests/shutdown).
    Raises RayTpuError when the records cannot be delivered (the
    background flusher instead warns once and retries)."""
    _Buffer.get().flush()


def flush_best_effort() -> None:
    """Flush without raising: a transient delivery failure requeues
    the batch for the background flusher instead of failing the
    caller (pre-read flushes in summaries and the doctor)."""
    _Buffer.get().flush(raise_on_error=False)


def _shutdown_buffer() -> None:
    """ray_tpu.shutdown() hook: stop the flusher and drop the
    singleton so re-init binds a fresh buffer to the new session."""
    _Buffer.reset()


def metrics_summary() -> Dict[str, dict]:
    """Cluster-wide aggregated metrics: {name: {kind, total/value/
    count, by_tags}}. The incidental pre-read flush is best-effort —
    a transient delivery failure requeues the batch for the
    background flusher instead of failing the read."""
    flush_best_effort()
    return _worker().call("metrics_summary")["metrics"]


def metrics_timeseries(
    name: Optional[str] = None,
    since: float = 0.0,
    limit: int = 0,
) -> List[dict]:
    """Historical metric snapshots from the head's bounded
    time-series ring, oldest first: ``[{"time", "metrics": {name:
    {kind, total/value/count/sum/p50/p95/p99, by_tags, by_node}}}]``.
    Counters rate-compute by differencing consecutive snapshots;
    histogram snapshots carry reservoir percentiles so p99 trends
    survive past the live window. `name` filters to one series,
    `since` (unix seconds) to newer-than, `limit` keeps the newest N
    snapshots."""
    flush_best_effort()
    kwargs: dict = {"since": float(since), "limit": int(limit)}
    if name is not None:
        kwargs["name"] = str(name)
    return _worker().call("metrics_timeseries", **kwargs)[
        "snapshots"
    ]
