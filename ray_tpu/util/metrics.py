"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py — application metrics recorded
from any worker, aggregated cluster-wide (the reference flows through
per-node metrics agents into Prometheus; here records flow through the
node daemon's KV-style metric table on the head and are queried with
`metrics_summary()`; a Prometheus text endpoint rides the dashboard).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .. import exceptions as exc

_FLUSH_INTERVAL_S = 0.5


def _worker():
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


class _Buffer:
    """Per-process record buffer with a background flusher."""

    _instance: Optional["_Buffer"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.records: List[tuple] = []
        self.records_lock = threading.Lock()
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    @classmethod
    def get(cls) -> "_Buffer":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def push(self, record: tuple) -> None:
        with self.records_lock:
            self.records.append(record)

    def _loop(self) -> None:
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            self.flush()

    def flush(self) -> None:
        with self.records_lock:
            batch, self.records = self.records, []
        if not batch:
            return
        try:
            _worker().call("metrics_record", records=batch)
        except Exception:
            pass


class _Metric:
    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Sequence[str] = (),
    ):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value < 0:
            raise ValueError("Counter.inc() takes a non-negative value")
        _Buffer.get().push(
            (self.KIND, self._name, float(value), self._tags(tags))
        )


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[dict] = None):
        _Buffer.get().push(
            (self.KIND, self._name, float(value), self._tags(tags))
        )


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = (),
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        self._boundaries = list(boundaries)

    def observe(self, value: float, tags: Optional[dict] = None):
        _Buffer.get().push(
            (self.KIND, self._name, float(value), self._tags(tags))
        )


def flush() -> None:
    """Force-flush this process's buffered records (tests/shutdown)."""
    _Buffer.get().flush()


def metrics_summary() -> Dict[str, dict]:
    """Cluster-wide aggregated metrics: {name: {kind, total/value/
    count, by_tags}}."""
    flush()
    return _worker().call("metrics_summary")["metrics"]
