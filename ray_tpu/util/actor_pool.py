"""ActorPool (reference: python/ray/util/actor_pool.py — submit work
to a fixed pool of actors, collecting results in or out of order)."""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        import ray_tpu as rt

        self._rt = rt
        self._idle = deque(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_submit = 0
        self._next_return = 0
        self._pending = deque()  # (fn, value) waiting for an actor

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef."""
        if self._idle:
            actor = self._idle.popleft()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._index_to_future[self._next_submit] = ref
            self._next_submit += 1
        else:
            self._pending.append((fn, value))

    def _drain_pending(self) -> None:
        while self._pending and self._idle:
            fn, value = self._pending.popleft()
            self.submit(fn, value)

    def has_next(self) -> bool:
        # Outstanding futures are the truth — index bookkeeping can't
        # be trusted after unordered consumption.
        return bool(self._future_to_actor) or bool(self._pending)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order.

        The actor is returned to the idle pool *before* the result is
        fetched (reference: python/ray/util/actor_pool.py:304) so that a
        task exception does not shrink the pool; a timeout while waiting
        leaves the pool state intact so the call can be retried.
        """
        if not self.has_next():
            raise StopIteration("no pending results")
        if self._next_return not in self._index_to_future:
            raise ValueError(
                "next ordered result was already consumed unordered"
            )
        ref = self._index_to_future[self._next_return]
        ready, _ = self._rt.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("next ordered result not ready in time")
        del self._index_to_future[self._next_return]
        self._next_return += 1
        self._idle.append(self._future_to_actor.pop(ref))
        self._drain_pending()
        return self._rt.get(ref)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Whichever pending result finishes first.

        Like get_next, the actor goes idle before the (possibly raising)
        get, so failed tasks don't permanently remove actors.
        """
        if not self.has_next():
            raise StopIteration("no pending results")
        refs = list(self._future_to_actor)
        ready, _ = self._rt.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result ready in time")
        ref = ready[0]
        for index, future in list(self._index_to_future.items()):
            if future is ref:
                del self._index_to_future[index]
                break
        self._idle.append(self._future_to_actor.pop(ref))
        self._drain_pending()
        return self._rt.get(ref)

    def map(self, fn: Callable, values: Iterable[Any]):
        for value in values:
            self.submit(fn, value)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for value in values:
            self.submit(fn, value)
        while self.has_next():
            yield self.get_next_unordered()
