"""Actor/task-level collective API.

Reference: python/ray/util/collective/collective.py:258-615 —
declared groups of actors run allreduce/allgather/reducescatter/
broadcast/send/recv/barrier over NCCL/GLOO backends.

TPU-native split (SURVEY.md §5.8): DENSE tensor collectives belong
inside the jitted program — ray_tpu.parallel.collective compiles them
to XLA ICI ops (psum/all_gather/ppermute), which is the NCCL
replacement and the fast path. THIS module is the control-plane
equivalent of the reference API for coordinating *processes*:
rendezvous + numpy reductions through the object store (the GLOO
role). Use it for gang bootstrap, small-state sync, and barriers —
not for gradients.

Implementation: a named rendezvous actor per group; rank 0 reduces
and publishes, other ranks exchange via the store.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

_GROUP_NAMESPACE = "_rt_collective"


class _Rendezvous:
    """Actor body: barrier + gather/publish per (group, op, seq); each
    round completes when its declared participant set has put."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        self._rounds: Dict[tuple, dict] = {}

    def put(self, op: str, seq: int, rank: int, value, expected=None):
        key = (op, seq)
        with self._lock:
            entry = self._rounds.setdefault(
                key,
                {
                    "values": {},
                    "expected": expected
                    or list(range(self.world_size)),
                },
            )
            entry["values"][rank] = value
        return True

    def ready(self, op: str, seq: int) -> bool:
        return self.gather(op, seq) is not None

    def gather(self, op: str, seq: int):
        with self._lock:
            entry = self._rounds.get((op, seq))
            if entry is None:
                return None
            expected = entry["expected"]
            if any(r not in entry["values"] for r in expected):
                return None
            # Dense list indexed by rank; non-participants hold None.
            out = [None] * self.world_size
            for rank in expected:
                out[rank] = entry["values"][rank]
            return out

    def clear(self, op: str, seq: int):
        with self._lock:
            self._rounds.pop((op, seq), None)
        return True


class CollectiveGroup:
    """One rank's handle (picklable: name + rank + size)."""

    def __init__(self, name: str, rank: int, world_size: int):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        # Per-op sequence counters: ops with different participant
        # sets (p2p vs group-wide) must not share one counter, or a
        # p2p pair desyncs everyone else's round numbering.
        self._seq: Dict[str, int] = {}

    def _actor(self):
        import ray_tpu as rt

        return rt.get_actor(
            f"collective:{self.name}", namespace=_GROUP_NAMESPACE
        )

    def _exchange(
        self,
        op: str,
        value,
        timeout: float,
        participants: Optional[List[int]] = None,
    ):
        """One rendezvous round. `participants` defaults to the whole
        group; p2p rounds pass the two endpoints."""
        import ray_tpu as rt

        actor = self._actor()
        seq = self._seq.get(op, 0)
        self._seq[op] = seq + 1
        expected = (
            sorted(participants)
            if participants is not None
            else list(range(self.world_size))
        )
        rt.get(
            actor.put.remote(op, seq, self.rank, value, expected),
            timeout=timeout,
        )
        deadline = time.time() + timeout
        while True:
            values = rt.get(
                actor.gather.remote(op, seq), timeout=timeout
            )
            if values is not None:
                if self.rank == expected[0]:
                    # Best-effort cleanup of the previous round once
                    # this one (which all participants reached) formed.
                    actor.clear.remote(op, seq - 1)
                return values
            if time.time() > deadline:
                raise TimeoutError(
                    f"collective {op} timed out in group "
                    f"{self.name!r} (rank {self.rank})"
                )
            time.sleep(0.005)

    # -- API (reference: collective.py allreduce:258 etc.) -----------
    def barrier(self, timeout: float = 60.0) -> None:
        self._exchange("barrier", None, timeout)

    def allreduce(
        self, tensor, op: str = "sum", timeout: float = 60.0
    ):
        values = self._exchange("allreduce", np.asarray(tensor), timeout)
        stack = np.stack(values)
        if op == "sum":
            return stack.sum(axis=0)
        if op == "mean":
            return stack.mean(axis=0)
        if op == "max":
            return stack.max(axis=0)
        if op == "min":
            return stack.min(axis=0)
        raise ValueError(f"unknown reduce op {op!r}")

    def allgather(self, tensor, timeout: float = 60.0) -> List:
        return self._exchange("allgather", np.asarray(tensor), timeout)

    def broadcast(
        self, tensor=None, src_rank: int = 0, timeout: float = 60.0
    ):
        values = self._exchange(
            "broadcast",
            np.asarray(tensor) if self.rank == src_rank else None,
            timeout,
        )
        return values[src_rank]

    def reducescatter(
        self, tensor, op: str = "sum", timeout: float = 60.0
    ):
        reduced = self.allreduce(tensor, op, timeout)
        shards = np.array_split(reduced, self.world_size)
        return shards[self.rank]

    def send(self, tensor, dst_rank: int, timeout: float = 60.0):
        self._exchange(
            f"p2p:{self.rank}->{dst_rank}",
            np.asarray(tensor),
            timeout,
            participants=[self.rank, dst_rank],
        )

    def recv(self, src_rank: int, timeout: float = 60.0):
        values = self._exchange(
            f"p2p:{src_rank}->{self.rank}",
            None,
            timeout,
            participants=[src_rank, self.rank],
        )
        return values[src_rank]


def init_collective_group(
    world_size: int,
    rank: int,
    group_name: str = "default",
) -> CollectiveGroup:
    """Join (rank 0 creates) a named collective group (reference:
    collective.init_collective_group)."""
    import ray_tpu as rt

    actor_name = f"collective:{group_name}"
    if rank == 0:
        actor_cls = rt.remote(
            num_cpus=0, name=actor_name, namespace=_GROUP_NAMESPACE
        )(_Rendezvous)
        actor = actor_cls.remote(world_size)
        rt.get(actor.ready.remote("init", 0), timeout=60)
    else:
        deadline = time.time() + 60
        while True:
            try:
                rt.get_actor(actor_name, namespace=_GROUP_NAMESPACE)
                break
            except ValueError:
                if time.time() > deadline:
                    raise
                time.sleep(0.02)
    return CollectiveGroup(group_name, rank, world_size)


def destroy_collective_group(group_name: str = "default") -> None:
    import ray_tpu as rt

    try:
        actor = rt.get_actor(
            f"collective:{group_name}", namespace=_GROUP_NAMESPACE
        )
        rt.kill(actor)
    except ValueError:
        pass
