"""Scheduling strategies for tasks and actors.

Reference: python/ray/util/scheduling_strategies.py —
NodeAffinitySchedulingStrategy:41, NodeLabelSchedulingStrategy:135,
plus the "SPREAD"/"DEFAULT" string strategies accepted by
`.options(scheduling_strategy=...)`. PlacementGroupSchedulingStrategy
is added with placement groups.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin a task/actor to a node. `soft=True` falls back to the
    default policy when the node is gone or infeasible."""

    node_id: str  # hex node id (from ray_tpu.nodes())
    soft: bool = False

    def to_spec(self) -> dict:
        return {
            "type": "NODE_AFFINITY",
            "node_id": self.node_id,
            "soft": self.soft,
        }


@dataclass
class NodeLabelSchedulingStrategy:
    """Match nodes by labels: `hard` must match; `soft` is preferred.
    Values map label key -> list of allowed values (empty = exists)."""

    hard: Dict[str, List[str]] = field(default_factory=dict)
    soft: Dict[str, List[str]] = field(default_factory=dict)

    def to_spec(self) -> dict:
        return {"type": "NODE_LABEL", "hard": self.hard, "soft": self.soft}


@dataclass
class PlacementGroupSchedulingStrategy:
    """Schedule a task/actor into a placement group's reserved bundle
    resources. `placement_group_bundle_index=-1` targets any bundle
    (the group's wildcard resources)."""

    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def to_spec(self) -> dict:
        return {
            "type": "PLACEMENT_GROUP",
            "pg_id": self.placement_group.id,
            "bundle_index": self.placement_group_bundle_index,
            "capture": self.placement_group_capture_child_tasks,
        }


def strategy_to_spec(strategy) -> dict | None:
    """Normalize a user-facing strategy option into the wire dict."""
    if strategy is None:
        return None
    if isinstance(strategy, str):
        if strategy not in ("DEFAULT", "SPREAD"):
            raise ValueError(f"unknown scheduling strategy {strategy!r}")
        return {"type": strategy}
    if hasattr(strategy, "to_spec"):
        return strategy.to_spec()
    raise TypeError(f"bad scheduling strategy: {strategy!r}")
