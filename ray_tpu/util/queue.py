"""Distributed queue (reference: python/ray/util/queue.py — a Queue
backed by an actor, usable from any worker)."""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items = deque()

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return (False, None)
        return (True, self.items.popleft())

    def qsize(self) -> int:
        return len(self.items)


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: dict = None):
        import ray_tpu as rt

        self._rt = rt
        actor_cls = rt.remote(**(actor_options or {"num_cpus": 0}))(
            _QueueActor
        )
        self._actor = actor_cls.remote(maxsize)

    def put(
        self,
        item: Any,
        block: bool = True,
        timeout: Optional[float] = None,
    ) -> None:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self._rt.get(self._actor.put.remote(item), timeout=30):
                return
            if not block:
                raise Full()
            if deadline is not None and time.time() > deadline:
                raise Full()
            time.sleep(0.01)

    def get(
        self, block: bool = True, timeout: Optional[float] = None
    ) -> Any:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            ok, item = self._rt.get(
                self._actor.get.remote(), timeout=30
            )
            if ok:
                return item
            if not block:
                raise Empty()
            if deadline is not None and time.time() > deadline:
                raise Empty()
            time.sleep(0.01)

    def qsize(self) -> int:
        return self._rt.get(self._actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self) -> None:
        try:
            self._rt.kill(self._actor)
        except Exception:
            pass

    def __reduce__(self):
        clone = object.__new__(Queue)
        return (_rebuild_queue, (self._actor,))


def _rebuild_queue(actor):
    import ray_tpu as rt

    queue = object.__new__(Queue)
    queue._rt = rt
    queue._actor = actor
    return queue
