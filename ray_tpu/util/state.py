"""State API: programmatic cluster introspection.

Reference: python/ray/util/state/api.py:110 — list_nodes/actors/tasks/
objects/placement_groups aggregated from the control plane; the CLI
(`ray list ...`, util/state/state_cli.py) prints the same tables.
"""

from __future__ import annotations

from typing import List, Optional

from .. import exceptions as exc


def _worker():
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


def list_nodes() -> List[dict]:
    return _worker().call("list_nodes")["nodes"]


def list_actors() -> List[dict]:
    return _worker().call("list_actors")["actors"]


def list_tasks(limit: int = 1000) -> List[dict]:
    events = _worker().call("list_task_events")["events"]
    # Collapse the event stream into latest-state-per-task (reference:
    # GcsTaskManager keeps per-task state transitions).
    latest = {}
    for event in events:
        latest[event["task_id"]] = event
    return list(latest.values())[:limit]


def list_objects(limit: int = 1000) -> List[dict]:
    return _worker().call("list_objects", limit=limit)["objects"]


def list_placement_groups() -> List[dict]:
    return _worker().call("placement_group_table")["table"]


def summarize() -> dict:
    return _worker().call("state_summary")["summary"]


def event_stats() -> dict:
    """Per-RPC-handler timing stats of the local daemon (count,
    mean/max execution and queueing delay — reference:
    src/ray/common/event_stats.cc debug dump). The first place to
    look when the control plane feels sluggish: a hot row with high
    exec time is a slow handler; uniformly high queue delay is a
    starved dispatch pool."""
    return _worker().call("event_stats")["handlers"]


__all__ = [
    "list_nodes",
    "list_actors",
    "list_tasks",
    "list_objects",
    "list_placement_groups",
    "summarize",
    "event_stats",
]
