"""State API: programmatic cluster introspection.

Reference: python/ray/util/state/api.py:110 — list_nodes/actors/tasks/
objects/placement_groups aggregated from the control plane; the CLI
(`ray list ...`, util/state/state_cli.py) prints the same tables.
"""

from __future__ import annotations

from typing import List, Optional

from .. import exceptions as exc


def _worker():
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


def list_nodes() -> List[dict]:
    return _worker().call("list_nodes")["nodes"]


def list_actors() -> List[dict]:
    return _worker().call("list_actors")["actors"]


def list_tasks(limit: int = 1000) -> List[dict]:
    events = _worker().call("list_task_events")["events"]
    # Collapse the event stream into latest-state-per-task (reference:
    # GcsTaskManager keeps per-task state transitions).
    latest = {}
    for event in events:
        latest[event["task_id"]] = event
    # Newest first BEFORE truncating: dict order here is event-stream
    # order, so a plain [:limit] under load dropped an arbitrary slice
    # of tasks — the recent ones an operator is actually after.
    rows = sorted(
        latest.values(),
        key=lambda e: float(e.get("time", 0.0)),
        reverse=True,
    )
    return rows[:limit]


def list_objects(limit: int = 1000) -> List[dict]:
    """Cluster object table, size-descending. The head sorts BEFORE
    applying `limit` (the old dict-order truncation dropped an
    arbitrary slice — the big consumers an operator is after; same
    bug class as the list_tasks newest-first fix). Rows carry the
    ledger's attribution columns (job, owner, age_s, spilled, pinned)
    and the data-plane columns: node (a copy holder), copies (how
    many nodes hold one), source (how this node's copy materialised:
    inline/local/pull/pull_spill/restore)."""
    rows = _worker().call("list_objects", limit=limit)["objects"]
    # Defensive re-sort: a pre-ledger head returns creation order.
    rows.sort(key=lambda r: int(r.get("size") or 0), reverse=True)
    return rows


def memory_summary() -> dict:
    """The cluster memory ledger (`ray_tpu memory` / `/api/memory`):
    arena totals + per-job attribution, per-(job, owner) bytes, top
    objects, per-node reports, spill/restore rates, and the doctor's
    `verdict.memory` (near-capacity nodes, leak suspects, spill
    thrash) over the same data."""
    return _worker().call("memory_summary", timeout=30.0)["memory"]


def transfer_summary() -> dict:
    """The cluster transfer matrix (`ray_tpu memory --transfers` /
    `/api/transfers`): per-(job, src_node, dst_node) flows with
    bytes/ms/pull/restore/abort counts, per-job get provenance
    (inline / local / pull / restore_local / restore_remote) and
    locality hit rates, the top remote-pulling task classes, and
    per-job spill/restore op totals."""
    return _worker().call("transfer_summary", timeout=30.0)[
        "transfers"
    ]


def object_locations(
    object_ids: Optional[List[str]] = None, limit: int = 1000
) -> List[dict]:
    """Head-side object location/size index: for each sealed object,
    the nodes holding a copy, its size, owner, and whether it is
    spilled — size-descending. `object_ids` (hex) filters to specific
    objects. This is the index the doctor's misplaced-task conviction
    reads; use it to check where a ref's bytes live before deciding
    where to schedule its consumer."""
    kwargs: dict = {"limit": int(limit)}
    if object_ids is not None:
        kwargs["oids"] = [bytes.fromhex(o) for o in object_ids]
    return _worker().call(
        "object_locations", timeout=30.0, **kwargs
    )["locations"]


def list_placement_groups() -> List[dict]:
    return _worker().call("placement_group_table")["table"]


def summarize() -> dict:
    return _worker().call("state_summary")["summary"]


def event_stats() -> dict:
    """Per-RPC-handler timing stats of the local daemon (count,
    mean/max execution and queueing delay — reference:
    src/ray/common/event_stats.cc debug dump). The first place to
    look when the control plane feels sluggish: a hot row with high
    exec time is a slow handler; uniformly high queue delay is a
    starved dispatch pool."""
    return _worker().call("event_stats")["handlers"]


def profile_worker(
    pid: int,
    *,
    kind: str = "cpu",
    duration_s: float = 5.0,
    hz: float = 100.0,
    top: int = 20,
    node_id: Optional[str] = None,
) -> dict:
    """Attach an on-demand profiler to a live worker process
    (reference: dashboard reporter profile_manager.py — py-spy
    cpu/stack profiles, memray memory profiles; here in-process,
    _private/profiling.py). kind: "cpu" (folded flamegraph stacks),
    "stack" (instant dump), "memory" (tracemalloc window). node_id
    (hex) targets a worker on another node."""
    kwargs: dict = {
        "pid": int(pid),
        "kind": kind,
        "duration_s": float(duration_s),
        "hz": float(hz),
        "top": int(top),
    }
    if node_id is not None:
        kwargs["node_id"] = bytes.fromhex(node_id)
    return _worker().call(
        "profile_worker", timeout=float(duration_s) + 40.0, **kwargs
    )


def profile_gang(
    job_id: Optional[str] = None,
    *,
    duration_s: float = 2.0,
    hz: float = 100.0,
    path: Optional[str] = None,
) -> dict:
    """Coordinated gang profiling: fan ONE synchronized start/stop
    window out to every step-reporting rank of a job (default: the
    most recently reporting job) and merge the per-rank captures —
    `jax.profiler` traces on TPU backends, the in-process timeline
    sampler elsewhere — with the gang's step-telemetry phases into
    one chrome trace on a shared unix-epoch clock. Returns
    ``{"job", "trace", "ranks", "errors", "window"}``; with `path`
    the merged trace is additionally written as chrome-trace JSON
    (load in chrome://tracing or Perfetto). CLI surface:
    ``ray_tpu profile --job``."""
    kwargs: dict = {
        "duration_s": float(duration_s),
        "hz": float(hz),
    }
    if job_id is not None:
        kwargs["job"] = str(job_id)
    reply = _worker().call(
        "profile_gang",
        timeout=float(duration_s) + 120.0,
        **kwargs,
    )
    if path is not None:
        import json

        with open(path, "w") as f:
            json.dump(reply.get("trace", []), f)
    return reply


def compile_summary() -> dict:
    """The head's folded XLA compile table: per-program compile
    counts/durations, the bounded shape-digest rings, and the current
    recompile-storm findings (`/api/compile`; the cluster half of
    `_private.compile_watch.snapshot()`)."""
    return _worker().call("compile_summary")["compile"]


__all__ = [
    "list_nodes",
    "list_actors",
    "list_tasks",
    "list_objects",
    "list_placement_groups",
    "memory_summary",
    "transfer_summary",
    "object_locations",
    "summarize",
    "event_stats",
    "profile_worker",
    "profile_gang",
    "compile_summary",
]
