"""User-facing TPU pod helpers.

Reference: python/ray/util/accelerators/tpu.py —
get_current_pod_name():7, get_current_pod_worker_count():21; plus the
slice-gang primitive SURVEY.md §7 phase 3 calls for: an atomic
"reserve all K hosts of one slice" built from a STRICT_SPREAD
placement group over the slice's per-host resources.
"""

from __future__ import annotations

from typing import Optional

from ..._private.accelerators.tpu import (
    TPUAcceleratorManager,
    chips_per_host,
    pod_type_num_chips,
    pod_worker_count,
)
from ..placement_group import PlacementGroup, placement_group


def get_current_pod_name() -> Optional[str]:
    """Name of the TPU pod this host belongs to (None off-TPU)."""
    return TPUAcceleratorManager.get_current_node_tpu_name()


def get_current_pod_worker_count() -> Optional[int]:
    """Number of hosts in this host's pod slice."""
    pod_type = TPUAcceleratorManager.get_current_node_accelerator_type()
    if pod_type is None:
        return None
    return pod_worker_count(pod_type)


def get_num_tpu_chips_on_node() -> int:
    return TPUAcceleratorManager.get_current_node_num_accelerators()


def slice_placement_group(
    pod_type: str,
    pod_name: Optional[str] = None,
    name: str = "",
) -> PlacementGroup:
    """Gang-reserve one whole TPU slice: one bundle per host, each
    claiming the host's full chip set, STRICT_SPREAD so bundles land on
    distinct hosts. Pass `pod_name` to pin the reservation to a
    specific slice (each of its hosts advertises `{pod_name}: 1`).

    The returned group is the scheduling unit for SPMD gangs: lease one
    worker per bundle and run the pjit program across them.
    """
    hosts = pod_worker_count(pod_type)
    per_host = chips_per_host(pod_type)
    bundle = {"TPU": float(per_host)}
    if pod_name:
        bundle[pod_name] = 1.0
    return placement_group(
        [dict(bundle) for _ in range(hosts)],
        strategy="STRICT_SPREAD",
        name=name,
    )


__all__ = [
    "get_current_pod_name",
    "get_current_pod_worker_count",
    "get_num_tpu_chips_on_node",
    "pod_type_num_chips",
    "slice_placement_group",
]
