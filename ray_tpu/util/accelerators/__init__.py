"""Accelerator helper APIs (reference: python/ray/util/accelerators/)."""

from . import tpu

__all__ = ["tpu"]
