"""Placement-group public API.

Reference: python/ray/util/placement_group.py — placement_group():145
creates a group with PACK/SPREAD/STRICT_PACK/STRICT_SPREAD strategies
(:162-164); PlacementGroup.ready() returns an ObjectRef gated on the
group's bundle-marker resource; remove_placement_group() tears the
group down and releases bundle resources.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .. import exceptions as exc
from .._private.ids import PlacementGroupID
from .._private.placement_groups import STRATEGIES, rewrite_request


def _worker():
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


class PlacementGroup:
    """Handle to a (possibly still-creating) placement group."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[dict]):
        self._pg_id = pg_id
        self._bundles = list(bundles)

    @property
    def id(self) -> str:
        return self._pg_id.hex()

    @property
    def bundle_specs(self) -> List[dict]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self):
        """ObjectRef that resolves once every bundle is committed.

        Implemented the reference's way: a no-op task whose resource
        request is the group's bundle-marker resource, so it can only
        schedule after commit (reference: placement_group.py ready()
        via bundle_reservation_check_func)."""
        from ..remote_function import RemoteFunction

        marker = rewrite_request({}, self.id, -1)

        def _bundle_reservation_check():
            return True

        rf = RemoteFunction(
            _bundle_reservation_check,
            {"num_cpus": 0, "resources": marker, "_skip_pg_rewrite": True},
        )
        return rf.remote()

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until the group is created (True) or timeout."""
        deadline = time.time() + timeout_seconds
        while True:
            if self.state() == "CREATED":
                return True
            if time.time() >= deadline:
                return False
            time.sleep(0.02)

    def state(self) -> Optional[str]:
        reply = _worker().call(
            "placement_group_state", pg_id=self._pg_id.binary()
        )
        return reply.get("state")

    def __reduce__(self):
        return (PlacementGroup, (self._pg_id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup({self.id[:12]}, {len(self._bundles)} bundles)"


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    """Asynchronously create a placement group; use `.wait()` or
    `.ready()` to block on creation."""
    if strategy not in STRATEGIES:
        raise ValueError(
            f"strategy must be one of {STRATEGIES}, got {strategy!r}"
        )
    if not bundles or not all(
        isinstance(b, dict) and b and all(v > 0 for v in b.values())
        for b in bundles
    ):
        raise ValueError(
            "bundles must be a non-empty list of non-empty "
            "{resource: amount>0} dicts"
        )
    pg_id = PlacementGroupID.from_random()
    clean = [{k: float(v) for k, v in b.items()} for b in bundles]
    reply = _worker().call(
        "create_placement_group",
        pg_id=pg_id.binary(),
        bundles=clean,
        strategy=strategy,
        name=name,
    )
    if reply.get("error"):
        raise ValueError(reply["error"])
    return PlacementGroup(pg_id, clean)


def remove_placement_group(pg: PlacementGroup) -> None:
    _worker().call(
        "remove_placement_group", pg_id=pg._pg_id.binary()
    )


def placement_group_table() -> List[dict]:
    return _worker().call("placement_group_table")["table"]


def get_placement_group(name: str) -> PlacementGroup:
    for entry in placement_group_table():
        if entry["name"] == name and entry["state"] != "REMOVED":
            return PlacementGroup(
                PlacementGroupID(bytes.fromhex(entry["placement_group_id"])),
                entry["bundles"],
            )
    raise ValueError(f"placement group {name!r} not found")
