"""Job submission: run driver scripts against a live cluster.

Reference: python/ray/dashboard/modules/job/ — JobSubmissionClient
(sdk.py) submits an entrypoint command; a JobSupervisor
(job_supervisor.py) runs it as a subprocess with the cluster address
injected, captures logs, and tracks status (job_manager.py). Here the
supervisor is a named JobManager actor on the cluster, so any client
process connected to the cluster can submit/inspect jobs.
"""

from __future__ import annotations

import enum
import os
import subprocess
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional

JOB_MANAGER_NAME = "_rt_job_manager"
_NAMESPACE = "_rt_jobs"


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobManager:
    """Actor body (reference: job_manager.py + per-job supervisor)."""

    def __init__(self, cluster_address: str):
        self._address = cluster_address
        self._jobs: Dict[str, dict] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._log_dir = tempfile.mkdtemp(prefix="rt_job_logs_")

    def submit(
        self,
        entrypoint: str,
        job_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        job_id = job_id or f"rtjob-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
            self._jobs[job_id] = {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "status": JobStatus.PENDING.value,
                "metadata": metadata or {},
                "start_time": time.time(),
                "end_time": None,
            }
        log_path = os.path.join(self._log_dir, f"{job_id}.log")
        env = dict(os.environ)
        env["RT_ADDRESS"] = self._address
        runtime_env = runtime_env or {}
        env.update(runtime_env.get("env_vars") or {})
        cwd = runtime_env.get("working_dir") or None
        log_file = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint,
                shell=True,
                env=env,
                cwd=cwd,
                stdout=log_file,
                stderr=subprocess.STDOUT,
            )
        except OSError as e:
            log_file.close()
            with self._lock:
                self._jobs[job_id]["status"] = JobStatus.FAILED.value
                self._jobs[job_id]["message"] = repr(e)
            return job_id
        log_file.close()  # child owns its copy of the fd
        with self._lock:
            self._jobs[job_id]["status"] = JobStatus.RUNNING.value
            self._jobs[job_id]["log_path"] = log_path
            self._procs[job_id] = proc
        threading.Thread(
            target=self._watch, args=(job_id, proc), daemon=True
        ).start()
        return job_id

    def _watch(self, job_id: str, proc: subprocess.Popen) -> None:
        code = proc.wait()  # rt: noqa[RT008] — a job runs until IT decides; liveness is the daemon's job
        with self._lock:
            job = self._jobs[job_id]
            if job["status"] == JobStatus.RUNNING.value:
                job["status"] = (
                    JobStatus.SUCCEEDED.value
                    if code == 0
                    else JobStatus.FAILED.value
                )
            job["end_time"] = time.time()
            job["exit_code"] = code
            self._procs.pop(job_id, None)

    def status(self, job_id: str) -> Optional[str]:
        with self._lock:
            job = self._jobs.get(job_id)
            return job["status"] if job else None

    def info(self, job_id: str) -> Optional[dict]:
        with self._lock:
            job = self._jobs.get(job_id)
            return dict(job) if job else None

    def logs(self, job_id: str) -> str:
        with self._lock:
            job = self._jobs.get(job_id)
        if not job or "log_path" not in job:
            return ""
        try:
            with open(job["log_path"], "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            job = self._jobs.get(job_id)
        if proc is None or job is None:
            return False
        proc.terminate()
        with self._lock:
            # The watcher may have recorded completion between our
            # snapshot and the terminate — don't overwrite a final
            # SUCCEEDED/FAILED with STOPPED.
            if job["status"] == JobStatus.RUNNING.value:
                job["status"] = JobStatus.STOPPED.value
        return True

    def list(self) -> List[dict]:
        with self._lock:
            return [dict(j) for j in self._jobs.values()]


class JobSubmissionClient:
    """(reference: dashboard/modules/job/sdk.py)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu as rt

        if not rt.is_initialized():
            rt.init(address=address, ignore_reinit_error=True)
        self._rt = rt
        self._manager = self._get_or_create_manager()

    def _get_or_create_manager(self):
        rt = self._rt
        try:
            return rt.get_actor(JOB_MANAGER_NAME, namespace=_NAMESPACE)
        except ValueError:
            pass
        from . import api as rt_api

        cluster_address = rt_api._session.address
        actor_cls = rt.remote(
            num_cpus=0, name=JOB_MANAGER_NAME, namespace=_NAMESPACE
        )(JobManager)
        manager = actor_cls.remote(cluster_address)
        rt.get(manager.list.remote(), timeout=60)
        return manager

    def submit_job(
        self,
        *,
        entrypoint: str,
        job_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        return self._rt.get(
            self._manager.submit.remote(
                entrypoint, job_id, runtime_env, metadata
            ),
            timeout=60,
        )

    def get_job_status(self, job_id: str) -> JobStatus:
        status = self._rt.get(
            self._manager.status.remote(job_id), timeout=30
        )
        if status is None:
            raise ValueError(f"no job {job_id!r}")
        return JobStatus(status)

    def get_job_info(self, job_id: str) -> dict:
        info = self._rt.get(self._manager.info.remote(job_id), timeout=30)
        if info is None:
            raise ValueError(f"no job {job_id!r}")
        return info

    def get_job_logs(self, job_id: str) -> str:
        return self._rt.get(self._manager.logs.remote(job_id), timeout=30)

    def stop_job(self, job_id: str) -> bool:
        return self._rt.get(
            self._manager.stop.remote(job_id), timeout=30
        )

    def list_jobs(self) -> List[dict]:
        return self._rt.get(self._manager.list.remote(), timeout=30)

    def wait_until_finished(
        self, job_id: str, timeout: float = 120.0
    ) -> JobStatus:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            if status in (
                JobStatus.SUCCEEDED,
                JobStatus.FAILED,
                JobStatus.STOPPED,
            ):
                return status
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} still {status}")
