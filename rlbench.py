#!/usr/bin/env python
"""RL dataflow benchmark: the decoupled Sebulba-style rollout/learner
split (ray_tpu/rl/dataflow.py, ISSUE 13) against the synchronous
sample -> update -> broadcast baseline, in the bench.py/servebench.py
JSON-trajectory idiom.

Prints ONE JSON line on the LAST stdout line and writes the full
result to RLBENCH.json:

  {"metric": "rlbench_env_steps_per_s", "value": N, "points": [...],
   "comparison": {...}, ...}

Design:

* THREE load points at IDENTICAL model/env geometry per point (same
  env, same policy net, same rollout length, same minibatch/epoch
  settings on both sides), sweeping the sample-vs-update cost ratio:
  `runner_bound` (light updates), `balanced` (the PPO defaults) and
  `learner_bound` (heavy updates — the regime the decoupled
  architecture exists for).
* Per point, three passes: the SYNCHRONOUS baseline (PPO.train's
  gather barrier — also phase-timed so the point records where its
  wall goes), the decoupled dataflow with LOCAL policy inference
  (identical per-step work: the comparison isolates the dataflow),
  and the decoupled dataflow with ENGINE-served inference (the RLHF
  shape: continuous batching over all runners' action requests,
  drainless weight pushes into the engine).
* Committed per point: env-steps/s, learner-updates/s, trained
  rows/s, weight-sync latency (median per update), queue occupancy
  (mean depth, capacity, backpressure/stale-gate counts), weight
  lag, and the `doctor --json` verdict.rl bottleneck attribution
  captured WHILE the dataflow runs.
* HONESTY on a 1-core box: in the runner-bound and balanced regimes
  sampling and learning time-share one core, so the decoupled path
  can only tie the baseline (committed as measured, ratios ~1x) —
  the same regime boundary PIPEBENCH documents. The headline is the
  learner-bound point, where the decoupled dataflow's bounded-
  staleness contract (queue capacity + max_weight_lag, drops
  COUNTED) lets actors keep sampling instead of idling behind the
  gather barrier: measured >= 2x env-steps/s with learner-updates/s
  and every dropped fragment committed beside it.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time

REPO = os.path.dirname(os.path.abspath(__file__))
OUT_PATH = os.path.join(REPO, "RLBENCH.json")

#: Load points: identical fleet/env geometry, update intensity swept.
#: queue/lag knobs only exist on the decoupled side (the baseline has
#: no queue); the learner-bound point uses the tighter lag bound the
#: staleness-drop accounting is about.
POINTS = [
    {
        "name": "runner_bound",
        "num_epochs": 1, "minibatch_size": 256,
        "queue_capacity": 16, "max_weight_lag": 4,
    },
    {
        "name": "balanced",
        "num_epochs": 4, "minibatch_size": 128,
        "queue_capacity": 16, "max_weight_lag": 4,
    },
    {
        # The headline regime: updates ~25x the sample cost. Queue
        # sized so runners free-run under the staleness bound
        # (capacity rejections ~0; what can't be trained in time is
        # DROPPED at get and counted) instead of being throttled by
        # capacity — measured 2.8x vs a 24/2 setting's 1.95x, same
        # model/env geometry.
        "name": "learner_bound",
        "num_epochs": 16, "minibatch_size": 32,
        "queue_capacity": 48, "max_weight_lag": 4,
    },
]

FLEET = {
    "num_env_runners": 2,
    "num_envs_per_runner": 8,
    "rollout_length": 64,
}

SMOKE_FLEET = {
    "num_env_runners": 2,
    "num_envs_per_runner": 4,
    "rollout_length": 32,
}


def _build(point: dict, fleet: dict, decoupled: bool, policy: str):
    from ray_tpu.rl import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=fleet["num_env_runners"],
            num_envs_per_env_runner=fleet["num_envs_per_runner"],
            rollout_fragment_length=fleet["rollout_length"],
        )
        .training(
            minibatch_size=point["minibatch_size"],
            num_epochs=point["num_epochs"],
        )
        .debugging(seed=0)
    )
    if decoupled:
        cfg.dataflow(
            policy=policy,
            queue_capacity=point["queue_capacity"],
            max_weight_lag=point["max_weight_lag"],
        )
    return cfg.build()


def run_sync(point: dict, fleet: dict, seconds: float) -> dict:
    """The synchronous baseline, phase-timed: one iteration = fan-out
    sample (gather barrier) + learner update + weight broadcast."""
    algo = _build(point, fleet, decoupled=False, policy="local")
    try:
        algo.train()  # warmup: compiles + first broadcast
        sample_ms, update_ms, bcast_ms = [], [], []
        steps = updates = 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < seconds:
            s0 = time.monotonic()
            batch = algo.env_runners.sample()
            batch.pop("episode_returns", None)
            s1 = time.monotonic()
            algo.learner.update(batch)
            s2 = time.monotonic()
            algo.env_runners.sync_weights(algo.learner.get_weights())
            s3 = time.monotonic()
            sample_ms.append((s1 - s0) * 1e3)
            update_ms.append((s2 - s1) * 1e3)
            bcast_ms.append((s3 - s2) * 1e3)
            steps += len(batch["obs"])
            updates += 1
        wall = time.monotonic() - t0
        return {
            "env_steps_per_s": round(steps / wall, 1),
            "updates_per_s": round(updates / wall, 3),
            "trained_rows_per_s": round(steps / wall, 1),
            "phases_ms": {
                "sample": round(statistics.median(sample_ms), 1),
                "update": round(statistics.median(update_ms), 1),
                "broadcast": round(statistics.median(bcast_ms), 1),
            },
        }
    finally:
        algo.stop()


def run_decoupled(
    point: dict, fleet: dict, seconds: float, policy: str,
    capture_doctor: bool = False,
) -> dict:
    import ray_tpu as rt

    algo = _build(point, fleet, decoupled=True, policy=policy)
    flow = algo.flow
    try:
        flow.train_update()  # warmup
        s0, q0 = flow.stats(), flow.queue_stats()
        sync_ms = []
        updates = 0
        rows_per_update = flow._update_rows
        doctor = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < seconds:
            metrics = flow.train_update()
            sync_ms.append(metrics["weight_sync_ms"])
            updates += 1
            if capture_doctor and doctor is None and updates >= 3:
                # Mid-run, traffic live: the verdict must attribute
                # the actor-vs-learner bottleneck from the rl_*
                # series while they are hot.
                from ray_tpu.util.metrics import flush_best_effort

                flush_best_effort()
                doctor = rt.diagnose(capture_stacks=False).get("rl")
        wall = time.monotonic() - t0
        s1, q1 = flow.stats(), flow.queue_stats()
        env_rate = (s1["env_steps"] - s0["env_steps"]) / wall
        out = {
            "env_steps_per_s": round(env_rate, 1),
            "updates_per_s": round(updates / wall, 3),
            "trained_rows_per_s": round(
                updates * rows_per_update / wall, 1
            ),
            "weight_sync_ms": {
                "p50": round(statistics.median(sync_ms), 2),
                "max": round(max(sync_ms), 2),
            },
            "weight_lag_bound": point["max_weight_lag"],
            "queue": {
                "capacity": q1["capacity"],
                "mean_depth": q1["mean_depth"],
                "rejected_full": q1["rejected_full"]
                - q0["rejected_full"],
                "rejected_stale": q1["rejected_stale"]
                - q0["rejected_stale"],
                "dropped_stale": q1["dropped_stale"]
                - q0["dropped_stale"],
                "empty_gets": q1["empty_gets"] - q0["empty_gets"],
            },
            "fragments_ok": s1["fragments_ok"] - s0["fragments_ok"],
            "fragments_dropped": s1["fragments_dropped"]
            - s0["fragments_dropped"],
            "runner_failures": s1["runner_failures"],
        }
        if policy == "engine":
            engine = flow.engine_stats() or {}
            steps = max(1, engine.get("policy_steps", 0))
            out["engine"] = {
                "policy_steps": engine.get("policy_steps", 0),
                "policy_rows_served": engine.get(
                    "policy_rows_served", 0
                ),
                "mean_batch_rows": round(
                    engine.get("policy_rows_served", 0) / steps, 2
                ),
                "weight_version": engine.get("weight_version", 0),
                "weight_gens": engine.get("weight_gens", 0),
            }
        if doctor is not None:
            out["doctor_rl"] = {
                "bottleneck": doctor.get("bottleneck"),
                "detail": doctor.get("detail"),
            }
        return out
    finally:
        algo.stop()


def _metrics_visibility() -> dict:
    """Do the acceptance series render on the Prometheus
    exposition? (the same text /metrics serves)."""
    try:
        from ray_tpu.util.metrics import (
            flush_best_effort,
            metrics_summary,
        )
        from ray_tpu.util.prometheus import render_prometheus

        flush_best_effort()
        time.sleep(0.8)  # one metrics-pipe flush interval
        text = render_prometheus(metrics_summary())
        return {
            name: name in text
            for name in (
                "rl_env_steps_total",
                "rl_learner_updates_total",
                "rl_queue_depth",
                "rl_queue_capacity",
                "rl_weight_lag",
                "rl_weight_version",
                "rl_weight_sync_ms",
                "serve_engine_weight_version",
                "serve_engine_policy_batch_ms",
            )
        }
    except Exception as e:  # noqa: BLE001 — visibility is reported,
        return {"error": str(e)}  # never fatal to the bench


def run_bench(args) -> dict:
    import ray_tpu as rt

    t_start = time.perf_counter()
    smoke = bool(args.smoke)
    fleet = dict(SMOKE_FLEET if smoke else FLEET)
    seconds = args.seconds or (5.0 if smoke else 12.0)
    points = POINTS if not smoke else [POINTS[0], POINTS[2]]
    rt.init(num_cpus=8)
    result_points = []
    visibility = {}
    try:
        for point in points:
            row = {
                "point": point["name"],
                "geometry": {**fleet, **{
                    k: point[k]
                    for k in ("num_epochs", "minibatch_size",
                              "queue_capacity", "max_weight_lag")
                }},
                "seconds": seconds,
            }
            row["baseline_sync"] = run_sync(point, fleet, seconds)
            row["decoupled_local"] = run_decoupled(
                point, fleet, seconds, "local", capture_doctor=True
            )
            if not args.no_engine:
                row["decoupled_engine"] = run_decoupled(
                    point, fleet, seconds, "engine"
                )
            base = row["baseline_sync"]["env_steps_per_s"]
            row["speedup_env_steps"] = round(
                row["decoupled_local"]["env_steps_per_s"]
                / max(base, 1e-9),
                2,
            )
            if "decoupled_engine" in row:
                row["speedup_env_steps_engine"] = round(
                    row["decoupled_engine"]["env_steps_per_s"]
                    / max(base, 1e-9),
                    2,
                )
            result_points.append(row)
        visibility = _metrics_visibility()
    finally:
        rt.shutdown()

    headline = result_points[-1]  # learner_bound
    result = {
        "metric": "rlbench_env_steps_per_s",
        "value": headline["decoupled_local"]["env_steps_per_s"],
        "comparison": {
            "point": headline["point"],
            "baseline_env_steps_per_s": headline["baseline_sync"][
                "env_steps_per_s"
            ],
            "decoupled_env_steps_per_s": headline[
                "decoupled_local"
            ]["env_steps_per_s"],
            "speedup": headline["speedup_env_steps"],
            "baseline_updates_per_s": headline["baseline_sync"][
                "updates_per_s"
            ],
            "decoupled_updates_per_s": headline["decoupled_local"][
                "updates_per_s"
            ],
        },
        "points": result_points,
        "metrics_visibility": visibility,
        "single_core_note": (
            "1-core box: sampling and learning time-share the CPU, "
            "so runner-bound/balanced points can only tie the "
            "baseline (measured, committed as-is). The headline is "
            "the learner-bound regime, where the decoupled path's "
            "bounded-staleness contract (queue capacity + "
            "max_weight_lag; every dropped fragment counted above) "
            "keeps actors sampling instead of idling behind the "
            "sync gather barrier. On >= 2 cores the balanced points "
            "gain overlap too."
        ),
        "smoke": smoke,
    }
    result["wall_s"] = round(time.perf_counter() - t_start, 1)
    return result


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--smoke", action="store_true",
        help="2 points, short windows: the whole dataflow + "
        "baseline on CPU in about a minute (CI-gated by "
        "tests/test_rlbench_smoke.py)",
    )
    parser.add_argument(
        "--seconds", type=float, default=None,
        help="measurement window per pass (default 12, 5 smoke)",
    )
    parser.add_argument(
        "--no-engine", action="store_true",
        help="skip the engine-served-policy passes",
    )
    parser.add_argument(
        "--out", default=OUT_PATH,
        help="result JSON path (default RLBENCH.json)",
    )
    args = parser.parse_args()
    result = run_bench(args)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
